"""Deterministic fault injection + transient-retry machinery.

The serve daemon (PR 7) and the overlap layer (PR 5) made sagecal-tpu
a long-lived multi-threaded service, but every I/O seam in it was
fail-stop: one transient MS read error killed the whole job. This
module holds the two halves of the fault-tolerance layer:

- **Injection** (:func:`inject` / :func:`fires`): a seedable,
  deterministic fault plan with NAMED injection points at every I/O
  and solve seam (:data:`POINTS`). Call sites are one attribute load
  + one ``is None`` test when no plan is installed — the same
  no-op-when-disabled contract as ``diag.trace`` and ``obs.metrics``
  (``faults.active()`` is a blessed telemetry-style gate for the
  jaxlint host-sync checker, like ``dtrace.active()``): faults off is
  bit-identical and compile-count-identical, gated in
  tests/test_faults.py and the sentinel's live probe. Determinism is
  order-independent: probabilistic rules draw from a stable hash of
  ``(seed, point, key, occurrence)`` so thread interleaving can never
  change which calls fire.

- **Retry** (:func:`retry_transient`): bounded
  exponential-backoff-with-jitter for TRANSIENT failures, with obs
  counters (``retries_total`` per retry, ``gave_up_total`` when the
  attempt budget is exhausted). On a non-transient exception — or
  once the budget is spent — the ORIGINAL exception re-raises with
  its original traceback, handing control to the existing fail-stop
  paths (AsyncWriter boundary check, Prefetcher propagation, serve
  per-job isolation). Wired into ``sched.Prefetcher`` (reads + host
  staging) and ``sched.AsyncWriter`` (MS residual tiles, solution
  rows, checkpoints); the retried jobs there are idempotent by
  construction (tile reads are pure; ``SimMS.write_tile`` is
  write-then-rename atomic; solution blocks land as ONE write).

Transience classification (:func:`is_transient`): injected
:class:`TransientFault`, ``ConnectionError``/``TimeoutError``/
``InterruptedError``, and ``OSError`` EXCEPT the shape-of-the-world
subclasses (``FileNotFoundError``, ``PermissionError``,
``IsADirectoryError``, ``NotADirectoryError``) — a missing dataset
will still be missing on attempt three, a flaky NFS read may not be.
Injected :class:`FatalFault` is never transient (the "permanent
failure" test lever).

Layering: stdlib + ``obs.metrics`` (itself stdlib-only) — importable
from every layer, including ``sched`` and ``io``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import zlib

from sagecal_tpu.obs import metrics as obs

#: every named injection point; an unknown point in a rule is an error
#: (a typo'd chaos plan silently injecting nothing is exactly the
#: failure mode a fault harness must refuse)
POINTS = (
    "ms_read",          # io/dataset: SimMS.read_tile entry
    "ms_write",         # io/dataset: SimMS.write_tile entry
    "solutions_write",  # io/solutions: SolutionWriter block write
    "beam_stage",       # pipeline: per-tile beam-table staging (reader)
    "residual_fetch",   # pipeline: residual d->h fetch (writer thread)
    "solve_nan",        # pipeline: poison a tile solve's residual
    "reader_thread",    # sched: Prefetcher producer death
    "writer_thread",    # sched: AsyncWriter job-loop death
    "socket_drop",      # serve/api: drop the client connection
    "migrate_abort",    # serve/scheduler: kill a job mid-migration,
    #                     AFTER its checkpoint flushed on the source
    #                     device and BEFORE its re-admission on the
    #                     target — the recovery path must re-queue the
    #                     job from the durable watermark (zero tiles
    #                     lost; chaos-gated in tests/test_faults.py)
    "worker_crash",     # serve/scheduler: kill the WHOLE WORKER
    #                     PROCESS (os._exit) at the tile boundary
    #                     entering tile ti, key "<job_id>:<ti>" — the
    #                     cross-process chaos lever: the router's
    #                     lease eviction must recover the dead
    #                     worker's jobs onto survivors from their
    #                     durable checkpoint watermarks (serve/
    #                     router.py; gated in tests/test_router.py).
    #                     Queried via fires(); only a process started
    #                     with a --faults plan can fire it, so it can
    #                     never kill a multi-tenant test process
    "admm_subband_slow",  # consensus/admm: a subband straggles for one
    #                     ADMM round (kind "transient": skipped under
    #                     bounded staleness, forced when the bound is
    #                     exhausted; kind "fatal": the subband is DEAD
    #                     — masked out of every later consensus).
    #                     Queried via draw(); key = subband index
    "tile_late",        # serve/scheduler + pipeline: force a streaming
    #                     tile past its per-tile arrival->write
    #                     deadline, key "<job_id>:<ti>" (serve) or the
    #                     tile index (direct runs). Queried via
    #                     fires(); the stream layer then applies its
    #                     own lateness policy — count, or degrade to
    #                     the last-good-Jones writeback — so the chaos
    #                     lever exercises the REAL late path, not a
    #                     synthetic clock skew
    "tile_dropped",     # stream transports: make the transport drop
    #                     tile i on the floor (never delivered), key =
    #                     tile index. The consumer observes the index
    #                     gap, counts stream_tiles_dropped_total and
    #                     continues — a live stream must survive loss
    #                     without stalling (gated in tests/
    #                     test_stream.py)
    "lock_acquire",     # analysis/threadsan: deterministic
    #                     interleaving pressure — an armed sanitizer
    #                     draws here on every instrumented lock
    #                     acquire (key = lock name) and stalls briefly
    #                     on a hit, widening race windows on the
    #                     plan's counted schedule instead of relying
    #                     on the OS scheduler to be unlucky. Queried
    #                     via draw(); only meaningful under
    #                     --sanitize-threads
)

_KINDS = ("transient", "fatal")

#: retry policy defaults (module attributes so tests/embedders can
#: tighten them; read at call time, never cached)
RETRY_ATTEMPTS = 3      # total attempts, including the first
RETRY_BASE_S = 0.05     # first backoff; doubles per retry
RETRY_MAX_S = 2.0       # backoff cap before jitter

_PLAN = None            # module-level singleton; None = disabled


class FaultError(RuntimeError):
    """Base class of every injected fault."""


class TransientFault(FaultError, OSError):
    """An injected fault the retry machinery should recover from."""


class FatalFault(FaultError):
    """An injected fault that must reach the fail-stop path."""


def _draw(seed: int, point: str, key, occ: int) -> float:
    """Stable uniform draw in [0, 1): a crc32 of the call identity, so
    probabilistic plans fire identically regardless of thread timing
    (Python's str hash is process-randomized — unusable here)."""
    h = zlib.crc32(repr((seed, point, key, occ)).encode())
    return (h & 0xFFFFFFFF) / 2.0 ** 32


class Rule:
    """One injection rule: WHERE (point), WHO (keys), HOW OFTEN
    (times / p), and WHAT (transient vs fatal)."""

    __slots__ = ("point", "kind", "at", "times", "p", "fired")

    def __init__(self, point: str, kind: str = "transient", at=None,
                 times: int | None = 1, p: float | None = None):
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; known: {POINTS}")
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"known: {_KINDS}")
        self.point = point
        self.kind = kind
        if at is None:
            self.at = None
        else:
            at = at if isinstance(at, (list, tuple, set)) else (at,)
            self.at = frozenset(at)
        self.times = None if times is None else int(times)
        self.p = None if p is None else float(p)
        self.fired = 0


class Plan:
    """An installed set of rules + the seed (thread-safe)."""

    def __init__(self, rules, seed: int = 0):
        self.rules = [r if isinstance(r, Rule) else Rule(**r)
                      for r in rules]
        self.seed = int(seed)
        self._occ: dict = {}       # (point, key) -> query count
        self._lock = threading.Lock()

    def match(self, point: str, key) -> Rule | None:
        """The first rule that fires for this call, or None; fired
        counts are consumed under the lock so concurrent seams (reader
        + writer threads) never double-fire a bounded rule."""
        with self._lock:
            k = (point, key)
            occ = self._occ[k] = self._occ.get(k, 0) + 1
            for r in self.rules:
                if r.point != point:
                    continue
                if r.at is not None and key not in r.at:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.p is not None and _draw(self.seed, point, key,
                                             occ) >= r.p:
                    continue
                r.fired += 1
                return r
        return None


# ---------------------------------------------------------------------------
# module-level no-op-when-disabled API (the diag.trace pattern)
# ---------------------------------------------------------------------------

def enable(rules, seed: int = 0) -> Plan:
    """Install a fault plan (a list of :class:`Rule` / rule dicts)."""
    global _PLAN
    _PLAN = Plan(rules, seed=seed)
    return _PLAN


def enable_spec(spec: str) -> Plan:
    """Install a plan from a CLI spec: a JSON list of rule dicts, a
    JSON object ``{"seed": ..., "rules": [...]}``, or ``@path`` / a
    readable path to a file holding either form."""
    text = spec
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            text = f.read()
    else:
        try:
            with open(spec) as f:
                text = f.read()
        except OSError:
            pass
    d = json.loads(text)
    if isinstance(d, dict):
        return enable(d.get("rules", []), seed=int(d.get("seed", 0)))
    return enable(d)


def disable() -> None:
    global _PLAN
    _PLAN = None


def get() -> Plan | None:
    return _PLAN


def active() -> bool:
    """True when a fault plan is installed — the blessed gate for call
    sites whose key computation is itself costly (none today)."""
    return _PLAN is not None


def fires(point: str, key=None) -> bool:
    """Value-corruption sites (``solve_nan``): True when a rule fires;
    the caller applies the corruption itself. False when disabled."""
    p = _PLAN
    if p is None:
        return False
    r = p.match(point, key)
    if r is None:
        return False
    obs.inc("faults_injected_total", point=point)
    return True


def draw(point: str, key=None) -> str | None:
    """Kind-preserving query sites (``admm_subband_slow``): the rule's
    ``kind`` ("transient"/"fatal") when one fires, else None — for
    callers whose response differs by kind (a slow subband is skipped
    for a round, a dead one is masked out for good) without raising
    through a device-dispatch loop. None when disabled."""
    p = _PLAN
    if p is None:
        return None
    r = p.match(point, key)
    if r is None:
        return None
    obs.inc("faults_injected_total", point=point)
    return r.kind


def inject(point: str, key=None) -> None:
    """Exception sites: raise :class:`TransientFault` /
    :class:`FatalFault` when a rule fires, else return. No-op (one
    attribute load, one ``is None`` test) when no plan is installed."""
    p = _PLAN
    if p is None:
        return
    r = p.match(point, key)
    if r is None:
        return
    obs.inc("faults_injected_total", point=point)
    if r.kind == "transient":
        raise TransientFault(
            f"injected transient fault: {point} (key={key})")
    raise FatalFault(f"injected fatal fault: {point} (key={key})")


# ---------------------------------------------------------------------------
# transient retry (the production half)
# ---------------------------------------------------------------------------

#: OSError subclasses that describe the world, not the weather — a
#: retry cannot conjure a missing file or a permission bit
_NON_TRANSIENT_OS = (FileNotFoundError, PermissionError,
                     IsADirectoryError, NotADirectoryError)


def is_transient(exc: BaseException) -> bool:
    if isinstance(exc, TransientFault):
        return True
    if isinstance(exc, FaultError):
        return False                       # FatalFault
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return True
    if isinstance(exc, OSError):
        return not isinstance(exc, _NON_TRANSIENT_OS)
    return False


def retry_transient(fn, args=(), kwargs=None, *, what: str = "io",
                    key=None, attempts: int | None = None,
                    base_s: float | None = None, log=None):
    """Run ``fn(*args, **kwargs)``, retrying TRANSIENT failures up to
    ``attempts`` total tries with exponential backoff + jitter. Counts
    ``retries_total{what=}`` per retry and ``gave_up_total{what=}``
    when the budget is exhausted, then re-raises the ORIGINAL
    exception (original traceback — the fail-stop contract downstream
    depends on it). Non-transient exceptions re-raise immediately,
    uncounted. ``fn`` must be idempotent up to its first durable side
    effect (the wired call sites are: reads are pure, writes are
    atomic or single-call)."""
    kwargs = kwargs or {}
    n = max(1, RETRY_ATTEMPTS if attempts is None else int(attempts))
    base = RETRY_BASE_S if base_s is None else float(base_s)
    for a in range(n):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not is_transient(e):
                raise
            if a == n - 1:
                obs.inc("gave_up_total", what=what)
                raise
            obs.inc("retries_total", what=what)
            delay = min(base * (2 ** a), RETRY_MAX_S)
            delay *= 0.5 + 0.5 * random.random()   # full-ish jitter
            if log is not None:
                log(f"transient {what} failure "
                    f"({type(e).__name__}: {e}); retry "
                    f"{a + 1}/{n - 1} in {delay * 1e3:.0f} ms"
                    + (f" (key={key})" if key is not None else ""))
            time.sleep(delay)
