"""Sky-model annotation CLI: DS9 regions + kvis annotations.

Capability parity with ``/root/reference/src/buildsky/annotate.py``
(flags -s/-c/-o/-n/-i/-C/-t: per-cluster fk5 ``point=x`` markers labeled
by cluster id or source name, optional az/el in the label); the az/el
path replaces python-casacore ``measures`` with the package's own
transforms (coords.radec2azel) at the reference's hardcoded LOFAR-core
ITRF position (annotate.py:27-29).

Additionally emits karma/kvis ``.ann`` annotations (--kvis), which the
reference tool family references (buildsky.c kvis pixel-numbering notes)
but never writes — CROSS markers + TEXT labels, and ELLIPSEs for
gaussians, in the documented kvis annotation-file syntax.
"""

from __future__ import annotations

import argparse
import math
import sys

import numpy as np

from sagecal_tpu import coords, skymodel

# LOFAR core ITRF meters (annotate.py:27-29)
LOFAR_X, LOFAR_Y, LOFAR_Z = 3826896.23513, 460979.454666, 5064658.203


def read_clusters(path):
    """{cluster_id_str: [source names]} preserving file order."""
    out = {}
    for cid, _h, names in skymodel.parse_cluster_file(path):
        out[str(cid)] = list(names)
    return out


def _azel(ra, dec, utc_s: float):
    """Az/el (degrees) at the LOFAR core for UTC seconds (the measures
    call of annotate.py:127-131, via coords.radec2azel)."""
    lon, lat, _h = coords.xyz2llh(LOFAR_X, LOFAR_Y, LOFAR_Z)
    jd = utc_s / 86400.0 + 2400000.5   # casacore UTC epoch is MJD secs
    az, el = coords.radec2azel(ra, dec, float(lon), float(lat), jd)
    return math.degrees(float(az)), math.degrees(float(el))


def annotate(skyfile, clusterfile, outfile, clid=None, color="yellow",
             rname=False, utc=None, kvis=False):
    srcs = skymodel.parse_sky_model(skyfile, 0.0, 0.0, 150e6)
    CL = read_clusters(clusterfile)
    annlist = ([str(clid)] if clid is not None and str(clid) in CL
               else list(CL))
    n = 0
    with open(outfile, "w") as f:
        if kvis:
            f.write("# karma annotation file (sagecal-tpu annotate)\n")
            f.write(f"COLOR {color.upper()}\n")
            f.write("COORD W\n")
        else:
            f.write("# Region file format: DS9 version 4.1\n")
            f.write('global color=blue dashlist=8 3 width=1 '
                    'font="helvetica 10 normal" select=1 highlite=1 '
                    'dash=0 fixed=0 edit=1 move=1 delete=1 include=1 '
                    'source=1\n')
        for clname in annlist:
            for slname in CL[clname]:
                if slname not in srcs:
                    continue
                s = srcs[slname]
                ra_d = math.degrees(s.ra % (2 * math.pi))
                dec_d = math.degrees(s.dec)
                label = slname if rname else clname
                if utc is not None:
                    az, el = _azel(s.ra, s.dec, float(utc))
                    label += f" {az:1.2f} {el:1.2f}"
                if kvis:
                    f.write(f"CROSS {ra_d:.6f} {dec_d:.6f} 0.002 0.002\n")
                    f.write(f"TEXT {ra_d:.6f} {dec_d:.6f} {label}\n")
                    if getattr(s, "stype", 0) and s.eX and s.eY:
                        f.write(f"ELLIPSE {ra_d:.6f} {dec_d:.6f} "
                                f"{math.degrees(s.eX):.6f} "
                                f"{math.degrees(s.eY):.6f} "
                                f"{math.degrees(s.eP):.2f}\n")
                else:
                    f.write(f"fk5;point({ra_d},{dec_d}) # point=x "
                            f"color={color} text={{{label}}}\n")
                n += 1
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="sagecal-tpu-annotate",
        description="DS9 regions / kvis annotations from sky + cluster "
                    "files")
    p.add_argument("-s", "--skymodel", required=True)
    p.add_argument("-c", "--clusters", required=True)
    p.add_argument("-o", "--outfile", required=True)
    p.add_argument("-n", "--names", dest="rname", action="store_true",
                   help="label with source names (default: cluster id)")
    p.add_argument("-i", "--id", type=int, dest="num", default=None,
                   help="cluster id to annotate (default all)")
    p.add_argument("-C", "--color", default="yellow")
    p.add_argument("-t", "--time", dest="utc", default=None,
                   help="UTC (s) for az/el labels")
    p.add_argument("--kvis", action="store_true",
                   help="write karma/kvis .ann instead of DS9 .reg")
    args = p.parse_args(argv)
    n = annotate(args.skymodel, args.clusters, args.outfile,
                 clid=args.num, color=args.color, rname=args.rname,
                 utc=args.utc, kvis=args.kvis)
    print(f"wrote {args.outfile}: {n} annotations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
