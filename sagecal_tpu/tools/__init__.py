"""Image-domain companion tools: buildsky (image -> sky model) and
restore (sky model -> image). Reference: src/buildsky/, src/restore/."""
