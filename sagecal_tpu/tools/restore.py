"""restore: render an LSM sky model (+ optional solutions) into a FITS
image, convolved with the restoring PSF.

Capability parity with the reference ``restore`` tool
(``src/restore/restore.c:862-880``): replace/add/subtract (-a/-s) the
rendered model in an existing FITS image; point sources rendered
analytically under the elliptical-Gaussian PSF; extended sources
(Gaussian/disk/ring/shapelet, by leading name letter) rendered in the
image domain (shapelet_lm.c Hermite basis) and FFT-convolved with the
PSF (fft.c); with ``-l solutions -c clusterfile`` each cluster's fluxes
are scaled by the mean apparent gain of its solutions
(readsky.c:460 ``read_sky_model_withgain``:
``sum(J_i)^H sum(J_i) - sum(J_i^H J_i)`` = sum_{p != q} J_p^H J_q over
station pairs, traced, averaged over timeslots; ``-g`` drops listed
stations). Solution application assumes an unpolarized model, as
upstream documents.

Beam-width convention matches buildsky: internal widths are HALF the
FWHM in radians and the PSF is ``exp(-(u^2+v^2))`` on pa-rotated
coordinates scaled by those half-widths.
"""

from __future__ import annotations

import argparse
import math
import sys

import numpy as np

from sagecal_tpu import skymodel
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.tools import fits as fitsio


def parse_bbs_sky(path: str, f0_default: float = 150e6) -> dict:
    """Minimal BBS catalog parser (-o 0; readsky.c:186
    ``read_bbs_skyline``): 'Name, Type, hh:mm:ss.s, dd.mm.ss.s, I, Q, U,
    V, RefFreq, [spectral_index]' lines -> {name: Source}."""
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("//"):
                continue
            tok = [t.strip() for t in line.split(",")]
            if len(tok) < 5 or ":" not in tok[2]:
                continue
            name = tok[0]
            hh, mm, ss = tok[2].split(":")
            ra = (float(hh) + float(mm) / 60 + float(ss) / 3600) \
                * math.pi / 12
            dparts = tok[3].split(".")
            dd = float(dparts[0])
            dmn = float(dparts[1]) if len(dparts) > 1 else 0.0
            dsc = float(".".join(dparts[2:])) if len(dparts) > 2 else 0.0
            sgn = -1.0 if tok[3].lstrip().startswith("-") else 1.0
            dec = sgn * (abs(dd) + dmn / 60 + dsc / 3600) * math.pi / 180
            sI = float(tok[4]) if len(tok) > 4 else 0.0
            f0 = float(tok[8]) if len(tok) > 8 and tok[8] else f0_default
            si = 0.0
            if len(tok) > 9:
                si_s = tok[9].strip("[]")
                si = float(si_s) if si_s else 0.0
            out[name] = skymodel.Source(
                name=name, ra=ra, dec=dec, ll=0.0, mm=0.0, nn=0.0,
                sI=sI, sQ=0.0, sU=0.0, sV=0.0, sI0=sI, sQ0=0.0, sU0=0.0,
                sV0=0.0, spec_idx=si, spec_idx1=0.0, spec_idx2=0.0, f0=f0)
    return out


def cluster_gains(solfile: str, cluster_path: str,
                  ignore_stations: set | None = None):
    """Per-cluster apparent-gain factors from a solutions file.

    factor_m = mean over (interval, chunk) of
      Re tr( sum_{p != q} J_p^H J_q ) / (2 N (N-1))
    (readsky.c:720-810) — the imaged Stokes-I scaling of an unpolarized
    source observed through per-station gains.
    Returns {cluster_id: factor}.
    """
    clusters = skymodel.parse_cluster_file(cluster_path)
    nchunk = np.array([max(1, nch) for _, nch, _ in clusters], np.int32)
    hdr, blocks = solio.read_solutions(solfile, nchunk)
    out = {}
    for mi, (cid, _, _) in enumerate(clusters):
        acc = 0.0
        cnt = 0
        for blk in blocks:
            J = blk[0] if isinstance(blk, list) else blk   # [M, K, N, 2, 2]
            for k in range(nchunk[mi]):
                Jk = J[mi, k]                              # [N, 2, 2]
                if ignore_stations:
                    keep = [p for p in range(Jk.shape[0])
                            if p not in ignore_stations]
                    Jk = Jk[keep]
                N = Jk.shape[0]
                if N < 2:
                    continue
                A = Jk.sum(axis=0)                         # sum_p J_p
                S2 = np.einsum("pij,pik->jk", Jk.conj(), Jk)
                cross = A.conj().T @ A - S2                # sum_{p!=q}
                acc += float(np.trace(cross).real) / (2.0 * N * (N - 1))
                cnt += 1
        out[int(cid)] = acc / cnt if cnt else 1.0
    return out


def _psf_kernel(img: fitsio.FitsImage, bmaj, bmin, bpa):
    """PSF image on the pixel grid, centered, for FFT convolution."""
    ny, nx = img.data.shape
    ys, xs = np.mgrid[0:ny, 0:nx]
    l, m = img.pixel_to_lm(xs, ys)
    lc, mc = img.pixel_to_lm(nx // 2, ny // 2)
    dl, dm = l - lc, m - mc
    sb, cb = math.sin(bpa), math.cos(bpa)
    u = (-dl * sb + dm * cb) / bmaj
    v = (-dl * cb - dm * sb) / bmin
    return np.exp(-(u * u + v * v))


def _hermite_1d(x, n0: int):
    """Normalized Hermite functions H_n(x) exp(-x^2/2) (hermite.c:
    recursion; shapelet_lm.c basis)."""
    H = [np.ones_like(x), 2.0 * x]
    for n in range(2, n0):
        H.append(2.0 * x * H[-1] - 2.0 * (n - 1) * H[-2])
    ex = np.exp(-0.5 * x * x)
    out = []
    for n in range(n0):
        norm = 1.0 / math.sqrt((2.0 ** n) * math.factorial(n)
                               * math.sqrt(math.pi))
        out.append(H[n] * ex * norm)
    return out


def render_source(img: fitsio.FitsImage, s, bmaj, bmin, bpa, l, m):
    """One source's contribution on the pixel grid (l, m precomputed by
    the caller). Points fold the PSF analytically; extended profiles are
    returned UNconvolved (the caller FFT-convolves the accumulated
    extended plane once)."""
    ls, ms = img.radec_to_lm(s.ra, s.dec)
    dl, dm = l - ls, m - ms
    stype = getattr(s, "stype", skymodel.STYPE_POINT)
    if stype == skymodel.STYPE_POINT:
        sb, cb = math.sin(bpa), math.cos(bpa)
        u = (-dl * sb + dm * cb) / bmaj
        v = (-dl * cb - dm * sb) / bmin
        return s.sI * np.exp(-(u * u + v * v)), True
    # rotate into the source frame (position angle from sky model)
    # rotate into the source frame by its catalogued position angle eP
    ce, se = math.cos(getattr(s, "eP", 0.0)), math.sin(getattr(s, "eP", 0.0))
    xr = dl * ce + dm * se
    yr = -dl * se + dm * ce
    # Extended profiles carry total flux sI, normalized by the ANALYTIC
    # profile integral (in pixels) so that partially-off-grid sources keep
    # only the flux that actually lands on the grid.
    pix_area = abs(img.cdelt1 * img.cdelt2)
    if stype == skymodel.STYPE_GAUSSIAN:
        # eX/eY carry the doubled readsky convention; use as 1/e widths
        eX, eY = max(s.eX, 1e-12), max(s.eY, 1e-12)
        prof = np.exp(-((xr / eX) ** 2 + (yr / eY) ** 2))
        prof *= s.sI / (math.pi * eX * eY / pix_area)
        return prof, False
    if stype == skymodel.STYPE_DISK:
        prof = ((xr ** 2 + yr ** 2) <= s.eX ** 2).astype(float)
        prof *= s.sI / max(math.pi * s.eX ** 2 / pix_area, 1.0)
        return prof, False
    if stype == skymodel.STYPE_RING:
        r = np.sqrt(xr ** 2 + yr ** 2)
        width = 1.5 * max(abs(img.cdelt2), 1e-12)
        prof = (np.abs(r - s.eX) < width).astype(float)
        prof *= s.sI / max(2 * math.pi * s.eX * 2 * width / pix_area, 1.0)
        return prof, False
    if stype == skymodel.STYPE_SHAPELET:
        # parse_sky_model already loaded the mode file onto the Source
        n0, beta = s.sh_n0, s.sh_beta
        hx = _hermite_1d(xr / beta, n0)
        hy = _hermite_1d(yr / beta, n0)
        prof = np.zeros_like(xr)
        mgrid = np.asarray(s.sh_modes).reshape(n0, n0)
        for n2 in range(n0):
            for n1 in range(n0):
                prof += mgrid[n2, n1] * hy[n2] * hx[n1]
        prof = prof / beta
        tot = prof.sum()
        if abs(tot) > 1e-300:
            prof *= s.sI / tot
        return prof, False
    return np.zeros_like(dl), True


def restore_image(img: fitsio.FitsImage, sources: dict, mode: str = "replace",
                  bmaj=None, bmin=None, bpa=None, gains=None,
                  source_cluster=None, log=print):
    """Render all sources into ``img.data`` (in place).

    mode: replace | add | subtract (-a / -s); gains: {cluster_id: factor}
    with ``source_cluster`` {name: cluster_id}.
    """
    bmaj = (bmaj if bmaj else img.bmaj) / 2 or 0.001
    bmin = (bmin if bmin else img.bmin) / 2 or 0.001
    bpa = bpa if bpa is not None else img.bpa
    ny, nx = img.data.shape
    ys, xs = np.mgrid[0:ny, 0:nx]
    l, m = img.pixel_to_lm(xs, ys)
    model = np.zeros_like(img.data)
    extended = np.zeros_like(img.data)
    n_ext = 0
    for s in sources.values():
        factor = 1.0
        if gains is not None and source_cluster is not None:
            factor = gains.get(source_cluster.get(s.name, None), 1.0)
        plane, convolved = render_source(img, s, bmaj, bmin, bpa, l, m)
        if convolved:
            model += factor * plane
        else:
            extended += factor * plane
            n_ext += 1
    if n_ext:
        # LINEAR convolution with the PSF: zero-pad to 2x so flux near an
        # edge falls off the grid instead of wrapping around (circular
        # FFT conv); "same" crop about the kernel center
        psf = _psf_kernel(img, bmaj, bmin, bpa)   # centered at (ny//2, nx//2)
        S = (2 * ny, 2 * nx)
        full = np.fft.irfft2(np.fft.rfft2(extended, s=S)
                             * np.fft.rfft2(psf, s=S), s=S)
        model += full[ny // 2:ny // 2 + ny, nx // 2:nx // 2 + nx]
    if mode == "add":
        img.data = img.data + model
    elif mode == "subtract":
        img.data = img.data - model
    else:
        img.data = model
    log(f"restore: {len(sources)} sources ({n_ext} extended), mode={mode}")
    return img


def build_parser():
    p = argparse.ArgumentParser(
        prog="sagecal-tpu-restore",
        description="render LSM (+solutions) into a FITS image")
    a = p.add_argument
    a("-f", "--fits", required=True)
    a("-i", "--sky-model", required=True)
    a("-o", "--format", type=int, default=2,
      help="0 BBS, 1 LSM, 2 LSM 3rd-order spectra (default)")
    a("-a", "--add", action="store_true")
    a("-s", "--subtract", action="store_true")
    a("-c", "--cluster-file", default=None)
    a("-l", "--solutions-file", default=None)
    a("-g", "--ignore-stations", default=None,
      help="file of station numbers to ignore")
    a("-m", "--bmaj", type=float, default=0.0, help="PSF major (arcsec)")
    a("-n", "--bmin", type=float, default=0.0)
    a("-p", "--bpa", type=float, default=0.0, help="PSF pa (deg)")
    a("-O", "--output", default=None, help="output FITS (default in-place)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    img = fitsio.read_fits(args.fits)
    if args.format == 0:
        sources = parse_bbs_sky(args.sky_model, img.freq or 150e6)
    else:
        sources = skymodel.parse_sky_model(
            args.sky_model, img.ra0, img.dec0,
            img.freq or 150e6, format_3=(args.format == 2))
    if not sources:
        print(f"no sources parsed from {args.sky_model} with -o "
              f"{args.format}; refusing to overwrite the image",
              file=sys.stderr)
        return 1
    gains = None
    source_cluster = None
    if args.solutions_file and args.cluster_file:
        ignore = set()
        if args.ignore_stations:
            with open(args.ignore_stations) as f:
                ignore = {int(t) for ln in f for t in ln.split()}
        gains = cluster_gains(args.solutions_file, args.cluster_file,
                              ignore)
        source_cluster = {}
        for cid, _, names in skymodel.parse_cluster_file(args.cluster_file):
            for nm in names:
                source_cluster[nm] = int(cid)
    mode = "add" if args.add else ("subtract" if args.subtract
                                   else "replace")
    kw = {}
    if args.bmaj:
        kw = dict(bmaj=math.radians(args.bmaj / 3600.0),
                  bmin=math.radians((args.bmin or args.bmaj) / 3600.0),
                  bpa=math.radians(args.bpa))
    restore_image(img, sources, mode=mode, gains=gains,
                  source_cluster=source_cluster, **kw)
    fitsio.write_fits(args.output or args.fits, img)
    return 0


if __name__ == "__main__":
    sys.exit(main())
