"""Sky-model clustering CLI — parity with the reference helper script
``/root/reference/src/buildsky/create_clusters.py`` (flags -s/-c/-o/-i,
negative cluster counts -> negative cluster ids) plus the generic
criteria of the reference's clustering library (``cluster.c`` k-means /
k-medians / linkage trees) via ``--method``.

The default method is the reference script's algorithm exactly
(cluster_lib.tangent_kmeans: Q-brightest init, great-circle assignment,
flux-weighted tangent-plane centroid updates, 5 iterations) so cluster
files produced here match the upstream tool on the same input.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from sagecal_tpu import skymodel
from sagecal_tpu.tools import cluster_lib as cl


def read_radec_flux(path):
    """(names, ra, dec, sI) from an LSM file, either spectra format
    (readsky.c:241 column layout; duplicated names: last wins, like the
    reference's dict)."""
    srcs = skymodel.parse_sky_model(path, 0.0, 0.0, 150e6)
    names = list(srcs.keys())
    ra = np.array([srcs[n].ra for n in names])
    dec = np.array([srcs[n].dec for n in names])
    sI = np.array([srcs[n].sI for n in names])
    return names, ra, dec, sI


def cluster_sky_model(path, Q: int, method: str = "tangent",
                      iterations: int = 5, seed: int = 0):
    """Returns (names, labels). ``Q`` < 0 requests |Q| clusters with
    negative ids at write time (the reference's convention for
    directions to subtract)."""
    names, ra, dec, sI = read_radec_flux(path)
    nq = min(abs(Q), len(names)) if Q else 1
    if method == "tangent":
        lab = cl.tangent_kmeans(ra, dec, sI, nq,
                                max_iterations=max(iterations, 2))
    elif method in ("kmeans", "kmedians"):
        l, m = cl.radec_to_lm_sin(float(np.mean(ra)), float(np.mean(dec)),
                                  ra, dec)
        lab, _ = cl.kcluster(np.stack([l, m], 1), nq,
                             method="m" if method == "kmedians" else "a",
                             seed=seed)
    elif method in cl._LINKAGES:
        l, m = cl.radec_to_lm_sin(float(np.mean(ra)), float(np.mean(dec)),
                                  ra, dec)
        lab = cl.linkage_labels(np.stack([l, m], 1), nq, method=method,
                                weight=np.abs(sI) + 1e-12
                                if method == "ward" else None)
    else:
        raise ValueError(f"unknown method {method!r}")
    return names, lab


def write_cluster_file(path, names, labels, negative: bool):
    """Reference output format (create_clusters.py:322-333): one line per
    cluster, ``id 1 name...``; ids 1-based, negated under ``negative``."""
    with open(path, "w") as f:
        f.write("# Cluster file\n")
        for c in np.unique(labels):
            cid = -(int(c) + 1) if negative else int(c) + 1
            members = [names[i] for i in np.where(labels == c)[0]]
            f.write(f"{cid} 1 " + " ".join(members) + "\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="sagecal-tpu-create-clusters",
        description="cluster an LSM sky model into calibration directions")
    p.add_argument("-s", "--skymodel", required=True)
    p.add_argument("-c", "--clusters", type=int, required=True,
                   help="number of clusters; negative -> negative ids")
    p.add_argument("-o", "--outfile", required=True)
    p.add_argument("-i", "--iterations", type=int, default=5)
    p.add_argument("--method", default="tangent",
                   choices=("tangent", "kmeans", "kmedians") + cl._LINKAGES,
                   help="tangent = reference create_clusters.py algorithm; "
                        "others = cluster.c library criteria")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    names, lab = cluster_sky_model(args.skymodel, args.clusters,
                                   method=args.method,
                                   iterations=args.iterations,
                                   seed=args.seed)
    write_cluster_file(args.outfile, names, lab, negative=args.clusters < 0)
    print(f"Read {len(names)} sources")
    print(f"wrote {args.outfile}: {len(np.unique(lab))} clusters")
    return 0


if __name__ == "__main__":
    sys.exit(main())
