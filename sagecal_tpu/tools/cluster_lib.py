"""Generic clustering library for sky-model tools.

Capability parity with the reference's embedded C Clustering Library
(``/root/reference/src/buildsky/cluster.c`` — distance metrics, k-means /
k-medians, hierarchical linkage trees + cuttree) and its spectral-
clustering driver (``scluster.c:675-748`` kmeans_clustering /
hierarchical_clustering), plus the tangent-plane weighted k-means of
``create_clusters.py:209-287`` (``cluster_this``). Re-implemented as
vectorized numpy — no GLib lists, no hand-rolled SVD; the algorithms are
standard and the parameterization follows the reference's.

The library is deliberately small: sky models are 10^2..10^5 sources, so
O(S^2) distance matrices and Lance-Williams agglomeration are fine — the
hot path of the framework is the calibration solvers, not this tool.
"""

from __future__ import annotations

import math

import numpy as np

# ---------------------------------------------------------------------------
# distance metrics (cluster.c:933-1500 'e','b','c','a','u','x','s')
# ---------------------------------------------------------------------------


def _rankdata(x):
    """Average-rank transform (cluster.c getrank:192 semantics)."""
    order = np.argsort(x, axis=-1)
    ranks = np.empty_like(order, dtype=float)
    n = x.shape[-1]
    arange = np.arange(n, dtype=float)
    np.put_along_axis(ranks, order, arange, axis=-1)
    # average ties
    out = ranks.copy()
    for i in range(x.shape[0]) if x.ndim == 2 else [None]:
        row = x[i] if i is not None else x
        rrow = ranks[i] if i is not None else ranks
        vals, inv, cnt = np.unique(row, return_inverse=True,
                                   return_counts=True)
        sums = np.zeros(len(vals))
        np.add.at(sums, inv, rrow)
        mean = sums / cnt
        if i is not None:
            out[i] = mean[inv]
        else:
            out = mean[inv]
    return out


def distance_matrix(data, weight=None, dist: str = "e"):
    """Pairwise distance matrix [S, S] over rows of ``data`` [S, D].

    ``dist`` follows cluster.c's metric letters:
      'e' euclidean (mean of weighted squared differences)
      'b' cityblock (mean of weighted absolute differences)
      'c' Pearson distance 1 - r            'a' absolute Pearson 1 - |r|
      'u' uncentered Pearson               'x' absolute uncentered
      's' Spearman rank distance
    Weights apply to 'e'/'b' (cluster.c euclid/cityblock); the
    correlation family is unweighted, like the reference defaults.
    """
    X = np.asarray(data, float)
    S, D = X.shape
    w = np.ones(D) if weight is None else np.asarray(weight, float)
    if dist == "e":
        diff = X[:, None] - X[None]
        return (diff * diff * w).sum(-1) / max(w.sum(), 1e-300)
    if dist == "b":
        diff = np.abs(X[:, None] - X[None])
        return (diff * w).sum(-1) / max(w.sum(), 1e-300)
    if dist in ("c", "a", "s"):
        Y = _rankdata(X) if dist == "s" else X
        Yc = Y - Y.mean(1, keepdims=True)
        nrm = np.sqrt((Yc * Yc).sum(1))
        nrm = np.where(nrm > 0, nrm, 1.0)
        r = (Yc @ Yc.T) / np.outer(nrm, nrm)
        return 1.0 - (np.abs(r) if dist == "a" else r)
    if dist in ("u", "x"):
        nrm = np.sqrt((X * X).sum(1))
        nrm = np.where(nrm > 0, nrm, 1.0)
        r = (X @ X.T) / np.outer(nrm, nrm)
        return 1.0 - (np.abs(r) if dist == "x" else r)
    raise ValueError(f"unknown distance {dist!r}")


# ---------------------------------------------------------------------------
# hierarchical linkage (cluster.c treecluster methods 's','m','a','c')
# ---------------------------------------------------------------------------

_LINKAGES = ("single", "complete", "average", "centroid", "ward")


def linkage_labels(data, n_clusters: int, method: str = "average",
                   weight=None, dist: str = "e"):
    """Agglomerate to ``n_clusters`` with the given linkage criterion.

    methods (cluster.c treecluster 's'/'m'/'a'/'c' + Ward):
      single / complete / average — Lance-Williams updates on the
      distance matrix (pslcluster/pmlcluster/palcluster,
      cluster.c:3386-3800);
      centroid — squared-euclidean centroid linkage with size-weighted
      centroid merges (pclcluster, cluster.c:3500);
      ward — minimum variance (weighted by row weights when given).

    Returns [S] labels 0..n_clusters-1.
    """
    X = np.asarray(data, float)
    S = len(X)
    nc = max(1, min(n_clusters, S))
    if S == 0:
        return np.zeros(0, int)
    if method not in _LINKAGES:
        raise ValueError(f"unknown linkage {method!r}; use {_LINKAGES}")

    if method in ("centroid", "ward"):
        # operate on centroids + member counts/weights
        cent = X.copy()
        cw = (np.ones(S) if weight is None
              else np.asarray(weight, float) + 1e-300)
        active = np.ones(S, bool)
        parent = np.arange(S)
        n_act = S
        while n_act > nc:
            idx = np.where(active)[0]
            C = cent[idx]
            d2 = ((C[:, None] - C[None]) ** 2).sum(-1)
            if method == "ward":
                wv = cw[idx]
                d2 = d2 * np.outer(wv, wv) / (wv[:, None] + wv[None])
            np.fill_diagonal(d2, np.inf)
            a, b = np.unravel_index(np.argmin(d2), d2.shape)
            ia, ib = idx[a], idx[b]
            m = cw[ia] + cw[ib]
            cent[ib] = (cw[ia] * cent[ia] + cw[ib] * cent[ib]) / m
            cw[ib] = m
            active[ia] = False
            parent[ia] = ib
            n_act -= 1

        def find(i):
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i
        roots = np.array([find(i) for i in range(S)])
        _, lab = np.unique(roots, return_inverse=True)
        return lab

    # distance-matrix linkages
    D = distance_matrix(X, weight, dist)
    np.fill_diagonal(D, np.inf)
    size = np.ones(S)
    active = np.ones(S, bool)
    parent = np.arange(S)
    n_act = S
    while n_act > nc:
        a, b = np.unravel_index(np.argmin(np.where(
            active[:, None] & active[None], D, np.inf)), D.shape)
        # Lance-Williams update of row/col b (the merged cluster)
        if method == "single":
            newd = np.minimum(D[a], D[b])
        elif method == "complete":
            newd = np.maximum(D[a], D[b])
        else:                      # average (UPGMA)
            newd = (size[a] * D[a] + size[b] * D[b]) / (size[a] + size[b])
        D[b] = newd
        D[:, b] = newd
        D[b, b] = np.inf
        size[b] += size[a]
        active[a] = False
        D[a] = np.inf
        D[:, a] = np.inf
        parent[a] = b
        n_act -= 1

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i
    roots = np.array([find(i) for i in range(S)])
    _, lab = np.unique(roots, return_inverse=True)
    return lab


# ---------------------------------------------------------------------------
# k-means / k-medians (cluster.c kcluster:1941, scluster.c:675)
# ---------------------------------------------------------------------------


def kcluster(data, n_clusters: int, weight=None, method: str = "a",
             npass: int = 5, seed: int = 0, maxiter: int = 100):
    """k-means (method 'a': arithmetic mean) or k-medians (method 'm')
    with weighted euclidean assignment — cluster.c kcluster semantics:
    ``npass`` random initializations, keep the lowest within-cluster
    error. Returns ([S] labels, error)."""
    X = np.asarray(data, float)
    S, Dn = X.shape
    nc = max(1, min(n_clusters, S))
    w = np.ones(Dn) if weight is None else np.asarray(weight, float)
    rng = np.random.default_rng(seed)
    best = (np.inf, np.zeros(S, int))
    for _ in range(max(1, npass)):
        cent = X[rng.choice(S, nc, replace=False)]
        lab = np.full(S, -1)
        for _ in range(maxiter):
            d = (((X[:, None] - cent[None]) ** 2) * w).sum(-1)
            new = np.argmin(d, 1)
            if np.array_equal(new, lab):
                break
            lab = new
            for c in range(nc):
                sel = lab == c
                if sel.any():
                    cent[c] = (np.median(X[sel], 0) if method == "m"
                               else X[sel].mean(0))
                else:
                    cent[c] = X[rng.integers(S)]
        err = float((((X - cent[lab]) ** 2) * w).sum())
        if err < best[0]:
            best = (err, lab.copy())
    return best[1], best[0]


# ---------------------------------------------------------------------------
# tangent-plane weighted k-means (create_clusters.py cluster_this:209-287)
# ---------------------------------------------------------------------------


def angular_distance(ra, dec, Cra, Cdec):
    """Great-circle distances [Q] from one source to Q centroids, the
    Vincenty arctan2 form of create_clusters.py:157-168 find_closest."""
    sda, cda = np.sin(Cra - ra), np.cos(Cra - ra)
    sd, cd = math.sin(dec), math.cos(dec)
    Cs, Cc = np.sin(Cdec), np.cos(Cdec)
    num = (Cc * sda) ** 2 + (cd * Cs - sd * Cc * cda) ** 2
    den = sd * Cs + cd * Cc * cda
    return np.arctan2(np.sqrt(num), den)


def radec_to_lm_sin(ra0, dec0, ra, dec):
    """SIN-projection (create_clusters.py:196-206)."""
    l = -np.sin(ra - ra0) * np.cos(dec)
    m = (-math.sin(dec0) * np.cos(ra - ra0) * np.cos(dec)
         + math.cos(dec0) * np.sin(dec))
    return l, m


def lm_to_radec(ra0, dec0, l, m):
    """Inverse SIN projection (create_clusters.py:173-193)."""
    sind0, cosd0 = math.sin(dec0), math.cos(dec0)
    d0 = m * m * sind0 * sind0 + l * l - 2 * m * cosd0 * sind0
    sind = math.sqrt(abs(sind0 * sind0 - d0))
    cosd = math.sqrt(abs(cosd0 * cosd0 + d0))
    sind = abs(sind) if sind0 > 0 else -abs(sind)
    dec = math.atan2(sind, cosd)
    if l != 0.0:
        ra = math.atan2(-l, cosd0 - m * sind0) + ra0
    else:
        ra = math.atan2(1e-10, cosd0 - m * sind0) + ra0
    return ra, dec


def tangent_kmeans(ra, dec, sI, Q: int, max_iterations: int = 5):
    """The reference ``cluster_this`` algorithm, faithfully:

    1. centroids start at the Q brightest sources;
    2. assign every source to the closest centroid by great-circle
       distance;
    3. per cluster, project members to the tangent plane at the current
       centroid (SIN), move the centroid to the flux-weighted mean;
    4. stop when assignments stop changing or after ``max_iterations``.

    Returns [S] labels (0-based cluster index in centroid order).
    """
    ra = np.asarray(ra, float)
    dec = np.asarray(dec, float)
    w = np.asarray(sI, float)
    S = len(ra)
    Q = max(1, min(Q, S))
    # Q brightest (argmax + zero-out, matching the reference's ties
    # behavior: first occurrence wins)
    tmp = w.copy()
    Cra = np.empty(Q)
    Cdec = np.empty(Q)
    for ci in range(Q):
        i = int(np.argmax(tmp))
        Cra[ci], Cdec[ci] = ra[i], dec[i]
        tmp[i] = 0.0
    lab = np.zeros(S, int)
    lab_old = lab.copy()
    for it in range(1, max_iterations):
        for i in range(S):
            lab[i] = int(np.argmin(np.abs(
                angular_distance(ra[i], dec[i], Cra, Cdec))))
        if it > 1 and np.array_equal(lab, lab_old):
            break
        lab_old = lab.copy()
        for c in np.unique(lab):
            sel = lab == c
            L, M = radec_to_lm_sin(Cra[c], Cdec[c], ra[sel], dec[sel])
            sw = w[sel].sum()
            Lm = float((w[sel] * L).sum() / sw)
            Mm = float((w[sel] * M).sum() / sw)
            Cra[c], Cdec[c] = lm_to_radec(Cra[c], Cdec[c], Lm, Mm)
    return lab


# ---------------------------------------------------------------------------
# principal components analysis (cluster.c:808-877 pca)
# ---------------------------------------------------------------------------


def pca(data):
    """Principal components analysis of a column-centered matrix.

    Capability parity with the reference ``pca()``
    (``/root/reference/src/buildsky/cluster.c:808-877``), which runs a
    hand-rolled Golub-Reinsch SVD; here it is one ``numpy.linalg.svd``
    call. ``data`` [nrows, ncolumns] is assumed column-mean-centered
    (same contract as the reference).

    Returns ``(coords, components, eigenvalues)``:

    - ``coords`` [nrows, n]: coordinates of each row w.r.t. the
      principal components (U @ diag(w));
    - ``components`` [n, ncolumns]: the principal component vectors
      (rows), so ``coords @ components`` reproduces ``data``;
    - ``eigenvalues`` [n]: eigenvalues of the covariance matrix
      (squared singular values), largest first,

    with ``n = min(nrows, ncolumns)``. The reference swaps which output
    array holds coordinates vs components depending on the matrix
    orientation purely to reuse its fixed-size buffers; this returns the
    same decomposition in one orientation for both cases.
    """
    a = np.asarray(data, float)
    if a.ndim != 2:
        raise ValueError("pca expects a 2-D matrix")
    u, w, vt = np.linalg.svd(a, full_matrices=False)
    return u * w, vt, w ** 2
