"""BBS <-> LSM sky-model converter CLI.

Capability parity with ``/root/reference/src/buildsky/convert_skymodel.py``
(flags -i/-o/-b/-l). Independent implementation: the reference drives two
giant regexes; here BBS lines are parsed as comma fields with optional
columns, LSM lines via the package's sky-model parser.

Conventions carried over from the reference:
- BBS -> LSM (:25): GAUSSIAN sources get a 'G' name prefix (the LSM
  name-prefix typing, readsky.c:405); BBS axes are FWHM arcsec ->
  LSM half-axes in rad (x 0.5/3600 deg->rad, :515-517); position angle
  maps as pi/2 - (pi - deg->rad) (:518); gaussians with axes < 1e-6 rad
  are dropped as bad (:519-521); missing Q/U/V/spectra default to 0.
- LSM -> BBS (:557): emits the BBS header + a CENTER patch stub, one
  ``name, POINT|GAUSSIAN, CENTER, h:m:s, d.m.s, I, Q, U, V, f0, [SI]``
  row per source, type chosen by the G name prefix.
"""

from __future__ import annotations

import argparse
import math
import sys

from sagecal_tpu import skymodel


def _parse_angle_ra(tok: str):
    h, m, s = tok.split(":")
    sign = -1.0 if h.strip().startswith("-") else 1.0
    val = abs(float(h)) + float(m) / 60.0 + float(s) / 3600.0
    return sign * val * 15.0 * math.pi / 180.0


def _parse_angle_dec(tok: str):
    d, m, s = tok.split(".", 2)
    sign = -1.0 if d.strip().startswith("-") else 1.0
    val = abs(float(d)) + float(m) / 60.0 + float(s) / 3600.0
    return sign * val * math.pi / 180.0


def _fmt_ra(ra: float):
    h = (ra % (2 * math.pi)) * 12.0 / math.pi
    hh = int(h)
    mm = int((h - hh) * 60)
    ss = ((h - hh) * 60 - mm) * 60
    return f"{hh}:{mm}:{ss:.4f}"


def _fmt_dec(dec: float):
    d = math.degrees(dec)
    sign = "-" if d < 0 else "+"
    d = abs(d)
    dd = int(d)
    mm = int((d - dd) * 60)
    ss = ((d - dd) * 60 - mm) * 60
    return f"{sign}{dd}.{mm}.{ss:.4f}"


def _floats(tok: str, default=0.0):
    tok = tok.strip()
    if not tok:
        return default
    return float(tok)


def parse_bbs(path):
    """Yield dicts from a BBS sky model; tolerant of the format's
    optional columns (patch present or not, gaussian axes, reference
    frequency, [spectral terms])."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(("#", "format", "(")):
                continue
            # spectral terms: strip the [...] block first
            spec = []
            if "[" in line:
                head, _, rest = line.partition("[")
                terms = rest.partition("]")[0]
                spec = [float(t) for t in terms.split(",") if t.strip()]
                line = head.rstrip().rstrip(",")
            toks = [t.strip() for t in line.split(",")]
            if len(toks) < 4 or not toks[0]:
                continue        # patch stubs like ", , CENTER, ..."
            name, stype = toks[0], toks[1].upper()
            if stype not in ("POINT", "GAUSSIAN"):
                continue
            k = 2
            if ":" not in toks[k]:
                k += 1          # skip the patch column when present
            try:
                ra = _parse_angle_ra(toks[k])
                dec = _parse_angle_dec(toks[k + 1])
            except (ValueError, IndexError):
                continue
            rest = toks[k + 2:]
            sI = _floats(rest[0]) if len(rest) > 0 else 0.0
            sQ = _floats(rest[1]) if len(rest) > 1 else 0.0
            sU = _floats(rest[2]) if len(rest) > 2 else 0.0
            sV = _floats(rest[3]) if len(rest) > 3 else 0.0
            rest = rest[4:]
            maj = mnr = pa = 0.0
            if stype == "GAUSSIAN" and len(rest) >= 3:
                maj = _floats(rest[0])
                mnr = _floats(rest[1])
                pa = _floats(rest[2])
                rest = rest[3:]
            f0 = _floats(rest[0], 0.0) if rest else 0.0
            out.append(dict(name=name, stype=stype, ra=ra, dec=dec,
                            sI=sI, sQ=sQ, sU=sU, sV=sV,
                            maj=maj, mnr=mnr, pa=pa, f0=f0 or 150e6,
                            spec=spec))
    return out


def bbs_to_lsm(infile, outfile):
    """Reference convert_sky_bbs_lsm semantics (:25-556)."""
    rows = parse_bbs(infile)
    nkept = 0
    with open(outfile, "w") as f:
        f.write("## LSM file converted from BBS format\n")
        f.write("# NAME RA(h m s) DEC(d m s) sI sQ sU sV SI RM eX eY eP "
                "freq0\n")
        for r in rows:
            name = r["name"]
            if r["stype"] == "GAUSSIAN":
                if not name.upper().startswith("G"):
                    name = "G" + name
                # BBS FWHM arcsec -> LSM half-axis rad (:515-517)
                eX = r["maj"] * (0.5 / 3600.0) * math.pi / 180.0
                eY = r["mnr"] * (0.5 / 3600.0) * math.pi / 180.0
                eP = math.pi / 2 - (math.pi - math.radians(r["pa"]))
                if eX < 1e-6 or eY < 1e-6:
                    continue    # bad gaussian (:519-521)
            else:
                eX = eY = eP = 0.0
            si = r["spec"][0] if r["spec"] else 0.0
            ra_h = (r["ra"] % (2 * math.pi)) * 12.0 / math.pi
            hh = int(ra_h)
            mm = int((ra_h - hh) * 60)
            ss = ((ra_h - hh) * 60 - mm) * 60
            dd_f = math.degrees(r["dec"])
            sgn = "-" if dd_f < 0 else ""
            dd_f = abs(dd_f)
            dd = int(dd_f)
            dm = int((dd_f - dd) * 60)
            dsec = ((dd_f - dd) * 60 - dm) * 60
            f.write(f"{name} {hh} {mm} {ss:.6f} {sgn}{dd} {dm} "
                    f"{dsec:.6f} {r['sI']} {r['sQ']} {r['sU']} {r['sV']} "
                    f"{si} 0 {eX:.8g} {eY:.8g} {eP:.8g} {r['f0']}\n")
            nkept += 1
    return nkept


def lsm_to_bbs(infile, outfile):
    """Reference convert_sky_lsm_bbs semantics (:557-666)."""
    srcs = skymodel.parse_sky_model(infile, 0.0, 0.0, 150e6)
    with open(outfile, "w") as f:
        f.write("# (Name, Type, Patch, Ra, Dec, I, Q, U, V, "
                "ReferenceFrequency='150e6',  SpectralIndex='[0.0]', "
                "Ishapelet) = format\n")
        f.write("# The above line defines the field order and is "
                "required.\n")
        f.write(", , CENTER, put:ra:here, put.dec.here\n")
        for name, s in srcs.items():
            gauss = name[:1].upper() == "G"
            stype = "GAUSSIAN" if gauss else "POINT"
            f.write(f"{name}, {stype}, CENTER, {_fmt_ra(s.ra)}, "
                    f"{_fmt_dec(s.dec)}, {s.sI}, {s.sQ}, {s.sU}, "
                    f"{s.sV}, {s.f0}, [{s.spec_idx}]\n")
    return len(srcs)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="sagecal-tpu-convert-skymodel",
        description="convert sky models between BBS and LSM formats")
    p.add_argument("-i", "--infile", required=True)
    p.add_argument("-o", "--outfile", required=True,
                   help="output sky model (overwritten!)")
    p.add_argument("-b", "--bbstolsm", action="store_true")
    p.add_argument("-l", "--lsmtobbs", action="store_true")
    args = p.parse_args(argv)
    if args.bbstolsm == args.lsmtobbs:
        p.error("choose exactly one of -b / -l")
    if args.bbstolsm:
        n = bbs_to_lsm(args.infile, args.outfile)
    else:
        n = lsm_to_bbs(args.infile, args.outfile)
    print(f"wrote {args.outfile}: {n} sources")
    return 0


if __name__ == "__main__":
    sys.exit(main())
