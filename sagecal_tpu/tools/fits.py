"""Minimal self-contained FITS image reader/writer.

The reference tools use cfitsio + wcslib (src/buildsky/buildsky.c
``read_fits_file``:242, src/restore/restore.c). This image has neither
cfitsio python bindings nor astropy, and the subset of FITS needed for
buildsky/restore is small: single-HDU images, BITPIX -32/-64/16/32,
NAXIS 2-4 (degenerate freq/stokes axes), linear or SIN-projected celestial
WCS, and the restoring-beam keywords BMAJ/BMIN/BPA. This module implements
exactly that subset over numpy big-endian buffers.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

BLOCK = 2880


@dataclasses.dataclass
class FitsImage:
    """A 2D image plane + the WCS/beam metadata the tools need."""

    data: np.ndarray            # [ny, nx] (row y, column x)
    ra0: float                  # reference RA (rad) at crpix
    dec0: float                 # reference Dec (rad)
    crpix1: float               # 1-based reference pixel (x)
    crpix2: float
    cdelt1: float               # rad/pixel (RA axis, usually negative)
    cdelt2: float
    bmaj: float = 0.0           # restoring beam major axis (rad)
    bmin: float = 0.0
    bpa: float = 0.0            # position angle (rad)
    freq: float = 0.0           # Hz (from a degenerate FREQ axis)
    header_cards: list = dataclasses.field(default_factory=list)

    @property
    def shape(self):
        return self.data.shape

    # --- WCS: SIN (orthographic) projection, the interferometric standard
    def pixel_to_lm(self, x, y):
        """0-based pixel -> direction cosines (l, m) about the reference
        direction. For SIN projection the tangent-plane offsets ARE l, m."""
        l = (np.asarray(x, float) - (self.crpix1 - 1.0)) * self.cdelt1
        m = (np.asarray(y, float) - (self.crpix2 - 1.0)) * self.cdelt2
        return l, m

    def lm_to_pixel(self, l, m):
        x = np.asarray(l, float) / self.cdelt1 + (self.crpix1 - 1.0)
        y = np.asarray(m, float) / self.cdelt2 + (self.crpix2 - 1.0)
        return x, y

    def lm_to_radec(self, l, m):
        """Inverse SIN projection about (ra0, dec0)."""
        l = np.asarray(l, float)
        m = np.asarray(m, float)
        n = np.sqrt(np.maximum(1.0 - l * l - m * m, 0.0))
        sd, cd = math.sin(self.dec0), math.cos(self.dec0)
        dec = np.arcsin(m * cd + n * sd)
        ra = self.ra0 + np.arctan2(l, n * cd - m * sd)
        return ra, dec

    def radec_to_lm(self, ra, dec):
        ra = np.asarray(ra, float)
        dec = np.asarray(dec, float)
        sd, cd = math.sin(self.dec0), math.cos(self.dec0)
        l = np.cos(dec) * np.sin(ra - self.ra0)
        m = np.sin(dec) * cd - np.cos(dec) * sd * np.cos(ra - self.ra0)
        return l, m


def _parse_card(card: bytes):
    key = card[:8].decode("ascii", "replace").strip()
    rest = card[8:].decode("ascii", "replace")
    if not rest.startswith("="):
        return key, None
    body = rest[1:]
    s = body.lstrip()
    if s.startswith("'"):
        # quoted string: '' escapes a quote; '/' inside quotes is literal
        # (e.g. BUNIT 'JY/BEAM'), so find the true closing quote first
        i, out = 1, []
        while i < len(s):
            if s[i] == "'":
                if i + 1 < len(s) and s[i + 1] == "'":
                    out.append("'")
                    i += 2
                    continue
                break
            out.append(s[i])
            i += 1
        return key, "".join(out).strip()
    val = body.split("/")[0].strip()
    if val in ("T", "F"):
        return key, val == "T"
    try:
        return key, int(val)
    except ValueError:
        pass
    try:
        return key, float(val)
    except ValueError:
        return key, val


def read_fits(path: str) -> FitsImage:
    with open(path, "rb") as f:
        raw = f.read()
    hdr = {}
    cards = []
    pos = 0
    done = False
    while not done:
        block = raw[pos:pos + BLOCK]
        if len(block) < BLOCK:
            raise ValueError(f"{path}: truncated FITS header")
        for i in range(0, BLOCK, 80):
            card = block[i:i + 80]
            k, v = _parse_card(card)
            if k == "END":
                done = True
                break
            if k:
                hdr[k] = v
                cards.append(card)
        pos += BLOCK

    bitpix = int(hdr["BITPIX"])
    naxis = int(hdr["NAXIS"])
    dims = [int(hdr[f"NAXIS{i+1}"]) for i in range(naxis)]
    count = int(np.prod(dims)) if dims else 0
    dt = {-64: ">f8", -32: ">f4", 16: ">i2", 32: ">i4", 8: ">u1"}[bitpix]
    need = count * np.dtype(dt).itemsize
    arr = np.frombuffer(raw[pos:pos + need], dtype=dt).astype(np.float64)
    if "BSCALE" in hdr or "BZERO" in hdr:
        arr = arr * float(hdr.get("BSCALE", 1.0)) + float(hdr.get("BZERO",
                                                                 0.0))
    # FITS is Fortran order: NAXIS1 fastest
    arr = arr.reshape(dims[::-1])
    # collapse degenerate leading (stokes/freq) axes to the 2D sky plane
    while arr.ndim > 2:
        arr = arr[0]

    # celestial + freq axes
    d2r = math.pi / 180.0
    ra0 = dec0 = 0.0
    crpix1 = crpix2 = 1.0
    cdelt1 = cdelt2 = 1.0 * d2r
    freq = 0.0
    for i in range(naxis):
        ctype = str(hdr.get(f"CTYPE{i+1}", ""))
        crval = float(hdr.get(f"CRVAL{i+1}", 0.0))
        cdelt = float(hdr.get(f"CDELT{i+1}", 1.0))
        crpix = float(hdr.get(f"CRPIX{i+1}", 1.0))
        if ctype.startswith("RA"):
            ra0, cdelt1, crpix1 = crval * d2r, cdelt * d2r, crpix
        elif ctype.startswith("DEC"):
            dec0, cdelt2, crpix2 = crval * d2r, cdelt * d2r, crpix
        elif ctype.startswith("FREQ"):
            freq = crval
    return FitsImage(
        data=arr, ra0=ra0, dec0=dec0, crpix1=crpix1, crpix2=crpix2,
        cdelt1=cdelt1, cdelt2=cdelt2,
        bmaj=float(hdr.get("BMAJ", 0.0)) * d2r,
        bmin=float(hdr.get("BMIN", 0.0)) * d2r,
        bpa=float(hdr.get("BPA", 0.0)) * d2r,
        freq=freq, header_cards=cards)


def _card(key: str, value, comment: str = "") -> bytes:
    if isinstance(value, bool):
        v = "T" if value else "F"
        s = f"{key:<8}= {v:>20}"
    elif isinstance(value, str):
        s = f"{key:<8}= '{value:<8}'"
    elif isinstance(value, int):
        s = f"{key:<8}= {value:>20}"
    else:
        s = f"{key:<8}= {value:>20.12E}"
    if comment:
        s += f" / {comment}"
    return s[:80].ljust(80).encode("ascii")


def write_fits(path: str, img: FitsImage) -> None:
    """Write a 2D (degenerate 4-axis) float32 image with SIN WCS."""
    ny, nx = img.data.shape
    r2d = 180.0 / math.pi
    cards = [
        _card("SIMPLE", True), _card("BITPIX", -32), _card("NAXIS", 4),
        _card("NAXIS1", nx), _card("NAXIS2", ny),
        _card("NAXIS3", 1), _card("NAXIS4", 1),
        _card("CTYPE1", "RA---SIN"), _card("CRVAL1", img.ra0 * r2d),
        _card("CDELT1", img.cdelt1 * r2d), _card("CRPIX1", img.crpix1),
        _card("CTYPE2", "DEC--SIN"), _card("CRVAL2", img.dec0 * r2d),
        _card("CDELT2", img.cdelt2 * r2d), _card("CRPIX2", img.crpix2),
        _card("CTYPE3", "FREQ"), _card("CRVAL3", img.freq),
        _card("CDELT3", 1.0), _card("CRPIX3", 1.0),
        _card("CTYPE4", "STOKES"), _card("CRVAL4", 1.0),
        _card("CDELT4", 1.0), _card("CRPIX4", 1.0),
        _card("BMAJ", img.bmaj * r2d), _card("BMIN", img.bmin * r2d),
        _card("BPA", img.bpa * r2d), _card("BUNIT", "JY/BEAM"),
    ]
    cards.append("END".ljust(80).encode("ascii"))
    hdr = b"".join(cards)
    hdr += b" " * ((-len(hdr)) % BLOCK)
    payload = img.data[None, None].astype(">f4").tobytes()
    payload += b"\x00" * ((-len(payload)) % BLOCK)
    with open(path, "wb") as f:
        f.write(hdr + payload)
