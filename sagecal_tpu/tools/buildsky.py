"""buildsky: FITS image + island mask -> LSM sky model + cluster file.

Capability parity with the reference ``buildsky`` tool
(``src/buildsky/``): per-island multi-point-source fits against the
restoring beam with AIC model-order selection (fitpixels.c:57-560,
buildsky.c:1286-1390 ``process_pixels``), EM component refinement,
sidelobe detection (``filter_pixels``, buildsky.c:1435), component
merging, flux rescaling, weighted k-means / hierarchical clustering of
sources into directions (cluster.c, create_clusters.py), and LSM/BBS
output with ds9 annotations (annotate.py).

Multi-FITS spectral mode (``-d`` directory; buildmultisky.c): positions
are fitted on the channel-mean image, per-channel fluxes solved linearly,
and up-to-3rd-order spectral indices fitted in log-log space
(``sI = exp(log I0 + sP log(f/f0) + sP1 log^2 + sP2 log^3)``).

Conventions follow the reference exactly:
- internal beam widths are HALF the FWHM in radians (main.c:210
  ``bmaj = (arcsec/3600)/360*pi``; buildsky.c:272 ``fits_bmaj/360*pi``),
  and the component model is ``sI * exp(-(u^2+v^2))`` with u, v the
  pa-rotated offsets scaled by those half-widths (fitpixels.c:90-95);
- AIC = 2*(3k) + 2*n*ln(SSE) — matching the reference CODE
  (fitpixels.c:103 ``2*3+npix*log(sumI)*2.0``; its comment says
  "2*k+N*ln" but the implementation doubles the data term);
- beam area in pixels = pi*bmaj*bmin/(|cdelt1*cdelt2|) (buildsky.c:288).
"""

from __future__ import annotations

import argparse
import glob
import math
import os
import sys

import numpy as np

from sagecal_tpu.tools import fits as fitsio


# ---------------------------------------------------------------------------
# island extraction
# ---------------------------------------------------------------------------

def label_islands(mask: np.ndarray) -> dict:
    """Island id -> (ys, xs) pixel indices. A Duchamp-style mask already
    carries distinct island numbers; a binary mask gets connected-component
    labels (4-connectivity, iterative flood fill)."""
    mask = np.asarray(mask)
    ids = np.unique(mask[mask > 0].astype(np.int64))
    if len(ids) > 1:
        return {int(i): np.nonzero(mask == i) for i in ids}
    # binary mask: label components
    lab = np.zeros(mask.shape, np.int64)
    cur = 0
    todo = list(zip(*np.nonzero(mask > 0)))
    seen = set()
    out = {}
    for seed in todo:
        if seed in seen:
            continue
        cur += 1
        stack = [seed]
        pix = []
        while stack:
            y, x = stack.pop()
            if (y, x) in seen or not (0 <= y < mask.shape[0]
                                      and 0 <= x < mask.shape[1]):
                continue
            if mask[y, x] <= 0:
                continue
            seen.add((y, x))
            lab[y, x] = cur
            pix.append((y, x))
            stack.extend([(y + 1, x), (y - 1, x), (y, x + 1), (y, x - 1)])
        ys = np.array([p[0] for p in pix])
        xs = np.array([p[1] for p in pix])
        out[cur] = (ys, xs)
    return out


# ---------------------------------------------------------------------------
# per-island fitting (fitpixels.c)
# ---------------------------------------------------------------------------

def _model_and_jac(p, l, m, sb, cb, bmaj, bmin, jac=True):
    """Sum of k PSF-shaped components + analytic Jacobian.

    p: [3k] = (l0, m0, sI0, l1, ...); returns (model [n], J [n, 3k]).
    u = (-dl*sb + dm*cb)/bmaj, v = (-dl*cb - dm*sb)/bmin,
    model += sI*exp(-(u^2+v^2)) (fitpixels.c:90-95).
    """
    k = len(p) // 3
    n = len(l)
    mod = np.zeros(n)
    J = np.zeros((n, 3 * k)) if jac else None
    for i in range(k):
        lk, mk, sk = p[3 * i], p[3 * i + 1], p[3 * i + 2]
        dl = l - lk
        dm = m - mk
        u = (-dl * sb + dm * cb) / bmaj
        v = (-dl * cb - dm * sb) / bmin
        E = np.exp(-(u * u + v * v))
        mod += sk * E
        if jac:
            # du/dlk = sb/bmaj, dv/dlk = cb/bmin
            J[:, 3 * i] = sk * E * (-2.0) * (u * sb / bmaj + v * cb / bmin)
            # du/dmk = -cb/bmaj, dv/dmk = sb/bmin
            J[:, 3 * i + 1] = sk * E * (-2.0) * (-u * cb / bmaj
                                                 + v * sb / bmin)
            J[:, 3 * i + 2] = E
    return mod, J


def _lm_refine(p0, l, m, x, sb, cb, bmaj, bmin, maxiter: int):
    """Damped LM on the k-component model (clmfit_nocuda.c equivalent)."""
    p = np.asarray(p0, float).copy()
    mod, J = _model_and_jac(p, l, m, sb, cb, bmaj, bmin)
    r = x - mod
    cost = r @ r
    mu = 1e-3 * max(np.max(np.abs(J.T @ J)), 1e-12)
    for _ in range(maxiter):
        JTJ = J.T @ J
        g = J.T @ r
        try:
            dp = np.linalg.solve(JTJ + mu * np.eye(len(p)), g)
        except np.linalg.LinAlgError:
            mu *= 10
            continue
        p_new = p + dp
        mod_new, J_new = _model_and_jac(p_new, l, m, sb, cb, bmaj, bmin)
        r_new = x - mod_new
        cost_new = r_new @ r_new
        if cost_new < cost:
            p, mod, J, r, cost = p_new, mod_new, J_new, r_new, cost_new
            mu = max(mu / 3, 1e-15)
            if np.linalg.norm(dp) < 1e-12:
                break
        else:
            mu *= 2.5
            if mu > 1e12:
                break
    return p, cost


def fit_island(l, m, x, bmaj, bmin, bpa, maxfits: int = 10,
               maxiter: int = 100, maxemiter: int = 4, use_em: bool = True):
    """AIC model-order scan: 1..maxfits components (process_pixels,
    buildsky.c:1286-1390). Returns (ll, mm, sI) of the best fit."""
    n = len(x)
    sb, cb = math.sin(bpa), math.cos(bpa)
    nfits = max(min(maxfits, n // 3), 1)
    best = None
    best_aic = np.inf
    for k in range(1, nfits + 1):
        if k == 1:
            # moment init (fit_single_point0, fitpixels.c:57) + LM refine
            # (fit_single_point, fitpixels.c:295)
            sumI = x.sum()
            if abs(sumI) < 1e-300:
                continue
            ll0 = float((x * l).sum() / sumI)
            mm0 = float((x * m).sum() / sumI)
            peak = x[np.argmax(np.abs(x))]
            p, sse = _lm_refine(np.array([ll0, mm0, peak]), l, m, x,
                                sb, cb, bmaj, bmin, maxiter)
            sse = float(sse)
        else:
            # greedy peak-subtract init (fit_N_point_em, fitpixels.c:478-)
            xd = x.copy()
            p = np.zeros(3 * k)
            for i in range(k):
                j = int(np.argmax(np.abs(xd)))
                p[3 * i:3 * i + 3] = (l[j], m[j], xd[j])
                mod, _ = _model_and_jac(p[3 * i:3 * i + 3], l, m, sb, cb,
                                        bmaj, bmin, jac=False)
                xd = xd - mod
            if use_em:
                # EM: cycle components, refit each against its residual
                for _ in range(maxemiter):
                    for i in range(k):
                        others = np.concatenate(
                            [p[:3 * i], p[3 * i + 3:]])
                        mod_o, _ = _model_and_jac(others, l, m, sb, cb,
                                                  bmaj, bmin, jac=False) \
                            if len(others) else (np.zeros(n), None)
                        pi, _ = _lm_refine(p[3 * i:3 * i + 3], l, m,
                                           x - mod_o, sb, cb, bmaj, bmin,
                                           max(maxiter // maxemiter, 5))
                        p[3 * i:3 * i + 3] = pi
            p, sse = _lm_refine(p, l, m, x, sb, cb, bmaj, bmin, maxiter)
            sse = float(sse)
        # keep components inside the island bounding box (hull penalty,
        # fitpixels.c:528-543)
        ok = True
        for i in range(k if k > 1 else 1):
            li, mi = p[3 * i], p[3 * i + 1]
            if not (l.min() - 2 * bmaj <= li <= l.max() + 2 * bmaj
                    and m.min() - 2 * bmaj <= mi <= m.max() + 2 * bmaj):
                ok = False
        aic = 2.0 * 3 * k + 2.0 * n * math.log(max(sse, 1e-300))
        if ok and aic < best_aic:
            best_aic = aic
            best = p.copy()
    if best is None:
        return np.array([]), np.array([]), np.array([])
    k = len(best) // 3
    return best[0::3][:k], best[1::3][:k], best[2::3][:k]


# ---------------------------------------------------------------------------
# post-processing
# ---------------------------------------------------------------------------

def convex_hull(l, m):
    """Convex hull of island pixels in (l, m) — Andrew's monotone chain.

    Capability parity with construct_boundary/hull.c (the reference uses a
    stack-based Graham scan); the hull bounds each island for annotation
    output and diagnostics.  Returns [H, 2] vertex array in CCW order.
    """
    pts = np.unique(np.stack([np.asarray(l, float),
                              np.asarray(m, float)], axis=1), axis=0)
    if len(pts) <= 2:
        return pts
    pts = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

    def cross(o, a, b):
        return ((a[0] - o[0]) * (b[1] - o[1])
                - (a[1] - o[1]) * (b[0] - o[0]))

    lower, upper = [], []
    for p in pts:
        while len(lower) >= 2 and cross(lower[-2], lower[-1], p) <= 0:
            lower.pop()
        lower.append(p)
    for p in pts[::-1]:
        while len(upper) >= 2 and cross(upper[-2], upper[-1], p) <= 0:
            upper.pop()
        upper.append(p)
    return np.asarray(lower[:-1] + upper[:-1])


def add_guard_pixels(xs, ys, l, m, x, img, threshold: float = 0.0):
    """Bounding-grid guard pixels (add_guard_pixels, buildsky.c:972-1260):
    every (x, y) on the island's x-coords x y-coords grid that is not an
    island pixel is appended with flux = min(island flux) * threshold
    (zero with the default threshold), anchoring the fit floor just
    outside the island. Returns extended (l, m, x)."""
    xs = np.asarray(xs)
    ys = np.asarray(ys)
    ux, uy = np.unique(xs), np.unique(ys)
    have = set(zip(xs.tolist(), ys.tolist()))
    gx, gy = np.meshgrid(ux, uy, indexing="ij")
    gxy = np.stack([gx.ravel(), gy.ravel()], axis=1)
    new = np.array([p for p in gxy if (int(p[0]), int(p[1])) not in have],
                   dtype=float)
    if len(new) == 0:
        return l, m, x
    gl, gm = img.pixel_to_lm(new[:, 0], new[:, 1])
    gflux = np.full(len(new), float(np.min(x)) * threshold)
    return (np.concatenate([l, gl]), np.concatenate([m, gm]),
            np.concatenate([x, gflux]))


def sidelobe_score(l, m, x):
    """Eigen-ratio sidelobe statistic (filter_pixels, buildsky.c:1460-1536):
    W0/(W1*peak*mean) — large for elongated faint islands."""
    lc = l - l.mean()
    mc = m - m.mean()
    a00 = (lc * lc).sum()
    a01 = (lc * mc).sum()
    a11 = (mc * mc).sum()
    T = a00 + a11
    D = a00 * a11 - a01 * a01
    s = math.sqrt(max(T * T * 0.25 - D, 0.0))
    w0, w1 = T * 0.5 + s, T * 0.5 - s
    peak = float(np.max(np.abs(x)))
    mean = float(np.abs(x.sum()) / len(x))
    denom = w1 * peak * mean
    return w0 / denom if denom > 0 else np.inf


def merge_components(ll, mm, sI, rd: float, bmaj: float, bmin: float):
    """Merge components closer than rd*(bmaj+bmin)/2 into flux-weighted
    centroids (-c; main.c:41)."""
    ll = list(map(float, ll))
    mm = list(map(float, mm))
    sI = list(map(float, sI))
    lim = rd * (bmaj + bmin) / 2
    merged = True
    while merged and len(ll) > 1:
        merged = False
        for i in range(len(ll)):
            for j in range(i + 1, len(ll)):
                if math.hypot(ll[i] - ll[j], mm[i] - mm[j]) < lim:
                    w = abs(sI[i]) + abs(sI[j])
                    if w > 0:
                        ll[i] = (abs(sI[i]) * ll[i] + abs(sI[j]) * ll[j]) / w
                        mm[i] = (abs(sI[i]) * mm[i] + abs(sI[j]) * mm[j]) / w
                    sI[i] = sI[i] + sI[j]
                    del ll[j], mm[j], sI[j]
                    merged = True
                    break
            if merged:
                break
    return np.array(ll), np.array(mm), np.array(sI)


def _sphere_vecs(ll, mm):
    """(l, m) tangent-plane coords -> [S, 3] unit vectors on the sphere.
    Angular distances between these equal the reference's great-circle
    metric (create_clusters.py find_closest, the Vincenty arctan2 form)."""
    nn = np.sqrt(np.clip(1.0 - ll * ll - mm * mm, 0.0, None))
    return np.stack([ll, mm, nn], 1)


def cluster_sources(ll, mm, sI, k: int, seed: int = 0, iters: int = 50,
                    init: str = "kmeans++"):
    """Cluster source directions into calibration directions.

    k > 0: flux-weighted spherical k-means with the reference semantics
    (``create_clusters.py cluster_this``): assignment by great-circle
    distance, centroid update = flux-weighted mean of member directions
    (the reference's project-to-tangent-plane weighted mean, to second
    order), stop when assignments no longer change. ``init``:

    - "kmeans++": first seed = brightest source, then D^2-sampling with
      flux x distance^2 probabilities (better objective on crowded
      fields than the reference's brightest-Q init);
    - "brightest": the reference's Q-brightest-sources init, for
      semantics-parity comparisons.

    k < 0: flux-weighted Ward agglomeration to |k| clusters via the
    nearest-neighbor-chain algorithm — merge cost
    d(ci, cj)^2 * wi wj / (wi + wj) — vectorized O(S^2) time / O(S)
    memory (the previous implementation was an O(S^3) Python loop;
    the reference's hierarchical modes live in cluster.c's generic
    linkage library).

    Returns [S] labels 0..nc-1.
    """
    S = len(ll)
    if k == 0 or S == 0:
        return np.zeros(S, int)
    w = np.abs(np.asarray(sI, float)) + 1e-12
    V = _sphere_vecs(np.asarray(ll, float), np.asarray(mm, float))
    nc = min(abs(k), S)
    if k > 0:
        if init not in ("kmeans++", "brightest"):
            raise ValueError(f"init={init!r}: use 'kmeans++' or "
                             f"'brightest'")
        rng = np.random.default_rng(seed)
        if init == "brightest":
            cent = V[np.argsort(-w)[:nc]].copy()
        else:                           # kmeans++ (flux-weighted D^2)
            cent = np.empty((nc, 3))
            cent[0] = V[np.argmax(w)]
            d2 = np.full(S, np.inf)
            for c in range(1, nc):
                d2 = np.minimum(d2, ((V - cent[c - 1]) ** 2).sum(1))
                p = w * d2
                tot = p.sum()
                if tot <= 0:            # all sources on chosen seeds
                    cent[c:] = V[rng.integers(S, size=nc - c)]
                    break
                cent[c] = V[rng.choice(S, p=p / tot)]
        lab = np.full(S, -1)
        for _ in range(max(iters, 1)):   # >=1 pass: labels always valid
            # chordal ~ monotone in great-circle distance: same argmin
            d = ((V[:, None] - cent[None]) ** 2).sum(-1)     # [S, nc]
            new = np.argmin(d, 1)
            if np.array_equal(new, lab):
                break                   # "cluster geometry did not change"
            lab = new
            for c in range(nc):
                sel = lab == c
                if sel.any():
                    m = (w[sel, None] * V[sel]).sum(0) / w[sel].sum()
                    cent[c] = m / max(np.linalg.norm(m), 1e-30)
                else:                   # empty cluster: reseed randomly
                    cent[c] = V[rng.integers(S)]
        return lab

    # --- flux-weighted Ward NN-chain agglomeration (k < 0)
    cent = V.copy()
    cw = w.copy()
    parent = np.arange(S)               # union-find for final labels
    active = np.ones(S, bool)
    n_active = S

    def ward_to(i):
        d2 = ((cent - cent[i]) ** 2).sum(1)
        cost = d2 * (cw * cw[i]) / (cw + cw[i])
        cost[i] = np.inf
        cost[~active] = np.inf
        return cost

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    chain = []
    while n_active > nc:
        if not chain:
            chain.append(int(np.argmax(active)))
        a = chain[-1]
        cost = ward_to(a)
        b = int(np.argmin(cost))
        if len(chain) > 1 and b == chain[-2]:
            # mutual nearest neighbors: merge a into b
            chain.pop()
            chain.pop()
            m = cw[a] + cw[b]
            cent[b] = (cw[a] * cent[a] + cw[b] * cent[b]) / m
            cw[b] = m
            active[a] = False
            parent[a] = b
            n_active -= 1
        else:
            chain.append(b)
    roots = np.array([find(i) for i in range(S)])
    _, lab = np.unique(roots, return_inverse=True)
    return lab


def cluster_by_method(ll, mm, sI, k: int, method: str = "kmeans++",
                      img=None, seed: int = 0):
    """Dispatch over every supported clustering criterion (--cluster-
    method): the in-house spherical k-means++/brightest and Ward
    (:func:`cluster_sources`), the generic linkage/k-cluster library
    (cluster_lib, parity with the reference's cluster.c criteria), and
    the reference create_clusters.py tangent-plane algorithm ("tangent",
    needs ``img`` for the projection center)."""
    from sagecal_tpu.tools import cluster_lib as cl
    if method in ("kmeans++", "brightest"):
        if k < 0:
            return cluster_sources(ll, mm, sI, k, seed=seed)   # Ward
        return cluster_sources(ll, mm, sI, k, seed=seed, init=method)
    nc = max(1, abs(int(k))) if k else 1
    X = np.stack([np.asarray(ll, float), np.asarray(mm, float)], 1)
    w = np.abs(np.asarray(sI, float)) + 1e-12
    if method == "ward":
        return cluster_lib_labels(X, nc, "ward", w)
    if method in ("single", "complete", "average", "centroid"):
        return cluster_lib_labels(X, nc, method, None)
    if method == "kmedians":
        return cl.kcluster(X, nc, method="m", seed=seed)[0]
    if method == "tangent":
        if img is None:
            raise ValueError("tangent method needs the FITS image center")
        pairs = [cl.lm_to_radec(img.ra0, img.dec0, float(l), float(m))
                 for l, m in zip(ll, mm)]
        ra = np.array([p[0] for p in pairs])
        dec = np.array([p[1] for p in pairs])
        return cl.tangent_kmeans(ra, dec, np.asarray(sI, float), nc)
    raise ValueError(f"unknown cluster method {method!r}")


def cluster_lib_labels(X, nc, method, w):
    from sagecal_tpu.tools import cluster_lib as cl
    return cl.linkage_labels(X, nc, method=method, weight=w)


# ---------------------------------------------------------------------------
# output (LSM format3 / BBS; cluster file; annotations)
# ---------------------------------------------------------------------------

def _radec_sexagesimal(ra, dec):
    ra = ra % (2 * math.pi)
    h = ra * 12.0 / math.pi
    rah = int(h)
    ram = int((h - rah) * 60)
    ras = ((h - rah) * 60 - ram) * 60
    neg = dec < 0
    d = abs(dec) * 180.0 / math.pi
    decd = int(d)
    decm = int((d - decd) * 60)
    decs = ((d - decd) * 60 - decm) * 60
    return rah, ram, ras, ("-" if neg else "") + str(decd), decm, decs


class SkySource:
    def __init__(self, name, ra, dec, l, m, sI, sP=0.0, sP1=0.0, sP2=0.0,
                 f0=1e9, isl=0):
        self.name = name
        self.ra, self.dec = ra, dec
        self.l, self.m = l, m
        self.sI, self.sP, self.sP1, self.sP2 = sI, sP, sP1, sP2
        self.f0 = f0
        self.isl = isl


def write_lsm(path, sources, fmt: int = 1):
    """fmt 0: BBS, 1: LSM with 3rd-order spectral indices (-o)."""
    with open(path, "w") as f:
        if fmt == 0:
            f.write("# (Name, Type, Ra, Dec, I, Q, U, V,"
                    " ReferenceFrequency, SpectralIndex) = format\n")
            for s in sources:
                rah, ram, ras, dd, dm_, dsx = _radec_sexagesimal(s.ra, s.dec)
                f.write(f"{s.name}, POINT, {rah}:{ram:02d}:{ras:06.3f}, "
                        f"{dd}.{dm_:02d}.{dsx:06.3f}, {s.sI:.6f}, 0, 0, 0, "
                        f"{s.f0:.1f}, [{s.sP:.4f}]\n")
        else:
            f.write("## LSM file (buildsky)\n"
                    "# name h m s d m s I Q U V spectral_index0 "
                    "spectral_index1 spectral_index2 RM eX eY eP "
                    "freq0\n")
            for s in sources:
                rah, ram, ras, dd, dm_, dsx = _radec_sexagesimal(s.ra, s.dec)
                f.write(f"{s.name} {rah} {ram} {ras:.4f} {dd} {dm_} "
                        f"{dsx:.4f} {s.sI:.6g} 0 0 0 {s.sP:.6g} "
                        f"{s.sP1:.6g} {s.sP2:.6g} 0 0 0 0 {s.f0:.6g}\n")


def write_cluster_file(path, sources, labels, nchunk: int = 1):
    """Cluster file rows: id chunks name...; brightest cluster first."""
    nc = labels.max() + 1 if len(labels) else 0
    flux = [sum(abs(s.sI) for s, c in zip(sources, labels) if c == ci)
            for ci in range(nc)]
    order = np.argsort(flux)[::-1]
    with open(path, "w") as f:
        f.write("# cluster_id chunks source_names\n")
        for new_id, ci in enumerate(order):
            names = " ".join(s.name for s, c in zip(sources, labels)
                             if c == ci)
            f.write(f"{new_id} {nchunk} {names}\n")


def write_ds9_regions(path, sources, hulls=None, img=None):
    """annotate.py equivalent: ds9 region file; island convex-hull
    boundary polygons when ``hulls`` (isl -> [H, 2] lm vertices) and the
    image (for lm -> ra/dec) are given (the reference draws hull
    boundaries in its annotations, buildsky.c:826-850)."""
    with open(path, "w") as f:
        f.write("# Region file format: DS9\nfk5\n")
        for s in sources:
            f.write(f'circle({math.degrees(s.ra):.6f},'
                    f'{math.degrees(s.dec):.6f},30") # text={{{s.name}}}\n')
        if hulls and img is not None:
            for isl, hv in sorted(hulls.items()):
                if len(hv) < 3:
                    continue
                ra, dec = img.lm_to_radec(hv[:, 0], hv[:, 1])
                pts = ",".join(f"{math.degrees(r):.6f},"
                               f"{math.degrees(d):.6f}"
                               for r, d in zip(ra, dec))
                f.write(f"polygon({pts}) # text={{island {isl}}}\n")


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def build_sky_single(img: fitsio.FitsImage, mask: np.ndarray,
                     threshold: float = 0.0, maxiter: int = 100,
                     maxemiter: int = 4, use_em: bool = True,
                     maxfits: int = 10, wcutoff: float = 0.0,
                     merge_rd: float = 0.0, unique: str = "",
                     ignore: set | None = None, donegative: bool = False,
                     scaleflux: bool = False, guard: bool = False,
                     log=print):
    """Single-image buildsky: returns (sources, sidelobe_ids)."""
    islands = label_islands(mask)
    bmaj = img.bmaj / 2 if img.bmaj else 0.001     # internal half-FWHM
    bmin = img.bmin / 2 if img.bmin else 0.001
    beam_pix = math.pi * bmaj * bmin / abs(img.cdelt1 * img.cdelt2)
    sources = []
    sidelobes = []
    hulls = {}
    for isl, (ys, xs) in sorted(islands.items()):
        if ignore and isl in ignore:
            continue
        l, m = img.pixel_to_lm(xs, ys)
        x = img.data[ys, xs].astype(float)
        if donegative:
            x = -x
        if threshold:
            x = np.where(np.abs(x) < threshold, 0.0, x)
        if not np.any(x):
            continue
        if wcutoff > 0 and len(x) > 2:
            if sidelobe_score(l, m, x) > wcutoff:
                sidelobes.append(isl)
        if len(x) > 2:
            hulls[isl] = convex_hull(l, m)
        if guard:
            # zero-floor guard ring on the island bounding grid
            # (add_guard_pixels, buildsky.c:1325) — opt-in: it anchors
            # extended-island fits but biases the AIC toward extra
            # components on compact islands
            lf, mf, xf = add_guard_pixels(xs, ys, l, m, x, img,
                                          threshold=threshold)
        else:
            lf, mf, xf = l, m, x
        ll, mm, sI = fit_island(lf, mf, xf, bmaj, bmin, img.bpa,
                                maxfits=maxfits, maxiter=maxiter,
                                maxemiter=maxemiter, use_em=use_em)
        if merge_rd > 0 and len(ll) > 1:
            ll, mm, sI = merge_components(ll, mm, sI, merge_rd, bmaj, bmin)
        if scaleflux and len(sI):
            tot_island = x.sum() / beam_pix
            tot_model = sI.sum()
            if abs(tot_model) > 0:
                sI = sI * (tot_island / tot_model)
        ra, dec = img.lm_to_radec(ll, mm)
        for ci in range(len(ll)):
            name = f"P{isl}C{ci}{unique}"
            if donegative:
                sI_out = -sI[ci]
            else:
                sI_out = sI[ci]
            sources.append(SkySource(name, float(ra[ci]), float(dec[ci]),
                                     float(ll[ci]), float(mm[ci]),
                                     float(sI_out), f0=img.freq or 1e9,
                                     isl=int(isl)))
    log(f"buildsky: {len(islands)} islands -> {len(sources)} sources")
    if sidelobes:
        log(f"probable sidelobe islands ({wcutoff}): "
            + " ".join(map(str, sidelobes)))
    return sources, sidelobes, hulls


def build_sky_multifreq(imgs: list, mask: np.ndarray, log=print, **kw):
    """Multi-FITS spectral mode (buildmultisky.c): positions from the
    channel-mean image, per-channel fluxes, log-log polynomial spectra."""
    freqs = np.array([im.freq for im in imgs])
    if np.any(freqs <= 0.0):
        raise ValueError(
            "spectral mode needs a FREQ axis in every FITS image "
            "(got freq<=0); add CTYPE/CRVAL FREQ cards")
    ref = imgs[0]
    mean_img = fitsio.FitsImage(
        data=np.mean([im.data for im in imgs], axis=0), ra0=ref.ra0,
        dec0=ref.dec0, crpix1=ref.crpix1, crpix2=ref.crpix2,
        cdelt1=ref.cdelt1, cdelt2=ref.cdelt2, bmaj=ref.bmaj,
        bmin=ref.bmin, bpa=ref.bpa, freq=float(freqs.mean()))
    sources, sidelobes, hulls = build_sky_single(mean_img, mask, log=log,
                                                 **kw)
    if not sources:
        return sources, sidelobes, hulls
    f0 = float(freqs.mean())
    bmaj, bmin = mean_img.bmaj / 2 or 0.001, mean_img.bmin / 2 or 0.001
    sb, cb = math.sin(mean_img.bpa), math.cos(mean_img.bpa)
    # restrict the flux solve to pixels of islands that actually produced
    # sources (ignored/failed islands would otherwise bias the lstsq)
    islands = label_islands(mask)
    used = {s.isl for s in sources}
    keep = [isl for isl in sorted(islands) if isl in used]
    ys = np.concatenate([islands[i][0] for i in keep])
    xs = np.concatenate([islands[i][1] for i in keep])
    l, m = mean_img.pixel_to_lm(xs, ys)
    # linear per-channel flux solve with fixed positions
    A = np.stack([_model_and_jac(
        np.array([s.l, s.m, 1.0]), l, m, sb, cb, bmaj, bmin,
        jac=False)[0] for s in sources], axis=1)       # [npix, S]
    lo = np.log(freqs / f0)
    fluxes = []
    for im in imgs:
        x = im.data[ys, xs].astype(float)
        sol, *_ = np.linalg.lstsq(A, x, rcond=None)
        fluxes.append(sol)
    fluxes = np.stack(fluxes)                          # [F, S]
    for si, s in enumerate(sources):
        fI = fluxes[:, si]
        pos = np.abs(fI) > 1e-12
        if pos.sum() >= 2:
            order = min(3, pos.sum() - 1)
            coeff = np.polyfit(lo[pos], np.log(np.abs(fI[pos])), order)
            coeff = coeff[::-1]       # ascending
            s.sI = math.copysign(math.exp(coeff[0]), np.median(fI))
            s.sP = float(coeff[1]) if order >= 1 else 0.0
            s.sP1 = float(coeff[2]) if order >= 2 else 0.0
            s.sP2 = float(coeff[3]) if order >= 3 else 0.0
        s.f0 = f0
    return sources, sidelobes, hulls


def build_parser():
    p = argparse.ArgumentParser(
        prog="sagecal-tpu-buildsky",
        description="FITS image + mask -> LSM sky model + cluster file")
    a = p.add_argument
    a("-f", "--image", help="FITS image")
    a("-d", "--fits-dir", help="directory of FITS images (spectral mode)")
    a("-m", "--mask", required=True, help="island mask FITS")
    a("-t", "--threshold", type=float, default=0.0)
    a("-i", "--maxiter", type=int, default=100)
    a("-e", "--maxemiter", type=int, default=4)
    a("-n", "--no-em", action="store_true")
    a("-a", "--bmaj", type=float, default=0.0, help="PSF major (arcsec)")
    a("-b", "--bmin", type=float, default=0.0)
    a("-p", "--bpa", type=float, default=0.0, help="PSF pa (deg)")
    a("-o", "--format", type=int, default=1,
      help="0 BBS, 1 LSM 3rd-order spectra (upstream buildsky numbering;"
           " note restore calls the 3rd-order format -o 2)")
    a("-g", "--ignorelist", default=None)
    a("-w", "--wcutoff", type=float, default=0.0)
    a("-c", "--merge", type=float, default=0.0)
    a("-l", "--maxfits", type=int, default=10)
    a("-k", "--clusters", type=int, default=0)
    a("--cluster-method", default="kmeans++",
      choices=("kmeans++", "brightest", "ward", "single", "complete",
               "average", "centroid", "kmedians", "tangent"),
      help="clustering criterion: in-house spherical k-means/Ward, the "
           "cluster.c-parity linkage/k-cluster library, or the "
           "create_clusters.py tangent-plane algorithm")
    a("-s", "--unique", default="")
    a("-N", "--negative", action="store_true")
    a("-q", "--scaleflux", type=int, default=0)
    a("-G", "--guard", action="store_true",
      help="add bounding-grid guard pixels at flux=min*threshold "
           "(reference add_guard_pixels; biases AIC on compact islands)")
    a("-O", "--output", default=None, help="output basename")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.image and not args.fits_dir:
        print("need -f image.fits or -d fits_dir", file=sys.stderr)
        return 1
    maskimg = fitsio.read_fits(args.mask)
    ignore = set()
    if args.ignorelist:
        with open(args.ignorelist) as f:
            ignore = {int(t) for line in f for t in line.split()}
    kw = dict(guard=args.guard,
              threshold=args.threshold, maxiter=args.maxiter,
              maxemiter=args.maxemiter, use_em=not args.no_em,
              maxfits=args.maxfits, wcutoff=args.wcutoff,
              merge_rd=args.merge, unique=args.unique, ignore=ignore,
              donegative=args.negative, scaleflux=bool(args.scaleflux))

    def override_beam(img):
        if args.bmaj:
            img.bmaj = math.radians(args.bmaj / 3600.0)
            # -a without -b: circular beam, not a zero/garbage minor axis
            img.bmin = math.radians((args.bmin or args.bmaj) / 3600.0)
            img.bpa = math.radians(args.bpa)
        return img

    if args.fits_dir:
        paths = sorted(glob.glob(os.path.join(args.fits_dir, "*.fits")))
        imgs = [override_beam(fitsio.read_fits(p)) for p in paths]
        sources, _, hulls = build_sky_multifreq(imgs, maskimg.data, **kw)
        img = imgs[0]
        base = args.output or (paths[0] + ".sky.txt")
    else:
        img = override_beam(fitsio.read_fits(args.image))
        sources, _, hulls = build_sky_single(img, maskimg.data, **kw)
        base = args.output or (args.image + ".sky.txt")

    write_lsm(base, sources, fmt=args.format)
    labels = cluster_by_method(
        np.array([s.l for s in sources]), np.array([s.m for s in sources]),
        np.array([s.sI for s in sources]), args.clusters,
        method=args.cluster_method, img=img)
    write_cluster_file(base + ".cluster", sources, labels)
    write_ds9_regions(base + ".reg", sources, hulls=hulls, img=img)
    print(f"wrote {base} (+.cluster, +.reg): {len(sources)} sources, "
          f"{labels.max() + 1 if len(labels) else 0} clusters")
    return 0


if __name__ == "__main__":
    sys.exit(main())
