"""sagecal-tpu: TPU-native direction-dependent radio interferometric calibration.

A ground-up JAX/XLA/Pallas re-design of the capabilities of SAGECal
(aroffringa/sagecal): direction-dependent calibration of radio
interferometer visibilities by expectation-maximization over sky
directions, with robust (Student's t) statistics, Riemannian
trust-region / LBFGS / Levenberg-Marquardt solvers, and distributed
consensus-ADMM across frequency subbands via `jax.sharding` meshes.

Layer map (mirrors reference SURVEY.md section 1, re-architected):

- ``sagecal_tpu.skymodel``  — sky-model/cluster parsing into padded struct-of-arrays
- ``sagecal_tpu.coords``    — celestial coordinate transforms
- ``sagecal_tpu.rime``      — visibility prediction (the RIME) in JAX
- ``sagecal_tpu.solvers``   — per-direction Jones solvers + SAGE-EM driver
- ``sagecal_tpu.consensus`` — frequency-consensus ADMM, polynomials, manifold ops
- ``sagecal_tpu.parallel``  — device mesh / sharding helpers
- ``sagecal_tpu.io``        — datasets, measurement-set access, solution files
"""

__version__ = "0.1.0"

from sagecal_tpu import config as config
