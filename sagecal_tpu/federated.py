"""Distributed stochastic calibration with federated averaging.

Capability parity with the reference's stochastic sagecal-mpi mode
(``sagecal-mpi -N > 0``; ``src/MPI/sagecal_stochastic_master.cpp`` +
``sagecal_stochastic_slave.cpp``): every "slave" (here: one subband
dataset; reference: one MPI rank with its MS list) runs minibatch
consensus calibration over its own frequency mini-bands with a LOCAL
polynomial consensus Z, and the slaves are coupled by FEDERATED
AVERAGING of their Z's:

- local Z update (slave :780-825): z = sum_b B_b Y_b (+ alpha Zavg - X
  after the first outer iteration), Z = Bii_fed z where Bii_fed is the
  inverse of (sum_b rho_b B_b B_b^T + alpha I)
  (``find_prod_inverse_full_fed``, consensus_poly.c);
- global Zavg = mean over slaves (stochastic master :329-351) — on a
  device mesh this is ``lax.pmean`` (SURVEY.md P11); host-looped slaves
  here compute the same mean directly;
- federated dual X += alpha (Z - Zavg) per cluster (slave :867-875);
- per-(slave, band) J updates are the stochastic consensus LBFGS solver
  (``bfgsfit_minibatch_consensus``), with diverged bands flagged out of
  the Z update exactly as the single-node mode does.

The J-update math runs jitted on the device per (slave, band,
minibatch); the Z/Zavg/X exchange is tiny (8 N Mt Npoly doubles per
slave) and stays on host, mirroring the reference's MPI exchange.
"""

from __future__ import annotations

import time

import numpy as np
import jax.numpy as jnp

from sagecal_tpu import skymodel, utils
from sagecal_tpu.config import RunConfig
from sagecal_tpu.consensus import poly as cpoly
from sagecal_tpu.io import dataset as ds
from sagecal_tpu.solvers import lbfgs as lbfgs_mod
from sagecal_tpu import stochastic as st

RES_RATIO = st.RES_RATIO


def run_federated(cfg: RunConfig, paths: list, log=print):
    """One invocation over several subband datasets (the slaves)."""
    mss = [ds.SimMS(p) for p in paths]
    meta0 = mss[0].meta
    sky = skymodel.read_sky_cluster(
        cfg.sky_model, cfg.cluster_file, meta0["ra0"], meta0["dec0"],
        float(np.mean([m.meta["freq0"] for m in mss])), cfg.format_3)
    nslaves = len(mss)
    runners = [st._StochasticRunner(cfg, m, sky, log=(lambda *a: None))
               for m in mss]
    rn0 = runners[0]
    log(f"Federated stochastic calibration: {nslaves} slave datasets, "
        f"{cfg.n_epochs} epochs x {rn0.minibatches} minibatches, "
        f"{rn0.nsolbw} mini-bands each, {cfg.n_admm} outer iterations")

    solver = st.make_band_solver(
        rn0.dsky, rn0.n, rn0.cidx, rn0.cmask, rn0.fdelta_chan,
        nu=cfg.robust_nulow, max_lbfgs=cfg.max_lbfgs, consensus=True,
        dobeam=rn0.dobeam)

    P = cfg.n_poly
    M, kmax, n = rn0.M, rn0.kmax, rn0.n
    ref_f = float(np.mean([m.meta["freq0"] for m in mss]))
    alpha = np.full(M, cfg.federated_alpha)

    # per-slave polynomial basis at that slave's band-center freqs
    Bs, Biis, rhoks = [], [], []
    for rn in runners:
        fcen = np.array([rn.freqs[c0:c0 + nc].mean()
                         for c0, nc in zip(rn.chanstart, rn.nchan)])
        B = cpoly.setup_polynomials(fcen, ref_f, P, cfg.poly_type)
        arho = np.full(M, cfg.admm_rho)
        if cfg.rho_file:
            arho = skymodel.read_cluster_rho(cfg.rho_file, sky.cluster_ids,
                                             cfg.admm_rho)
        rhok = np.tile(arho[None, :], (rn.nsolbw, 1))       # [nb, M]
        # federated inverse: +alpha I (find_prod_inverse_full_fed)
        Bii = np.asarray(cpoly.find_prod_inverse(
            jnp.asarray(B), jnp.asarray(rhok.T), alpha=jnp.asarray(alpha)))
        Bs.append(B)
        Biis.append(Bii)
        rhoks.append(rhok)

    pshape = (M, kmax, n, 8)
    states = []
    for rn in runners:
        pinit, pfreq = rn.initial_p()
        mems = [lbfgs_mod.lbfgs_memory_init(rn.nparam, cfg.lbfgs_m)
                for _ in range(rn.nsolbw)]
        states.append({"pfreq": pfreq, "mems": mems, "pinit": pinit,
                       "res_prev": None})

    writer = rn0.solution_writer()
    n_tiles = min(m.n_tiles for m in mss)
    start = cfg.skip_timeslots           # -K (CTRL_SKIP, master :623-634)
    stop = n_tiles if not cfg.max_timeslots else min(
        n_tiles, start + cfg.max_timeslots)
    history = []
    for ti in range(start, stop):
        t0 = time.time()
        tiles = [m.read_tile(ti) for m in mss]
        for rn, tile in zip(runners, tiles):
            rn.prepare_tile(tile)
        Zavg = np.zeros((M, P, kmax, n, 8))
        Zs = [np.zeros_like(Zavg) for _ in range(nslaves)]
        Xs = [np.zeros_like(Zavg) for _ in range(nslaves)]
        Ys = [np.zeros((rn.nsolbw,) + pshape) for rn in runners]
        resband = [np.zeros(rn.nsolbw) for rn in runners]
        res_0 = res_1 = 0.0
        for nadmm in range(cfg.n_admm):
            r0all, r1all = [], []
            for s, rn in enumerate(runners):
                B, Bii, rhok = Bs[s], Biis[s], rhoks[s]
                Y, Z, X = Ys[s], Zs[s], Xs[s]
                pfreq, mems = states[s]["pfreq"], states[s]["mems"]
                for nepch in range(cfg.n_epochs):
                    for nmb in range(rn.minibatches):
                        r0s, r1s = [], []
                        for b in range(rn.nsolbw):
                            BZ = np.einsum("p,mpkns->mkns", B[b], Z)
                            args = rn.band_inputs(nmb, b)
                            out = solver(
                                *args, jnp.asarray(pfreq[b], rn.rdt),
                                mems[b], Y=jnp.asarray(Y[b], rn.rdt),
                                BZ=jnp.asarray(BZ, rn.rdt),
                                rho=jnp.asarray(rhok[b], rn.rdt),
                                beam=rn.tile_beam)
                            pfreq[b] = np.asarray(out.p)
                            mems[b] = out.mem
                            r00, r01 = float(out.res_0), float(out.res_1)
                            resband[s][b] = r01 if (r00 > 0 and r01 > 0) \
                                else np.inf
                            r0s.append(r00)
                            r1s.append(r01)
                        rmean = float(np.mean(r1s))
                        fband = resband[s] > RES_RATIO * rmean
                        good = ~fband
                        # local ADMM update (slave :780-825)
                        for b in np.where(good)[0]:
                            Y[b] += (rhok[b][:, None, None, None]
                                     * pfreq[b])
                        zsum = np.einsum("b,bp,bmkns->mpkns",
                                         good.astype(float), B, Y)
                        if nadmm > 0:
                            zsum += (alpha[:, None, None, None, None]
                                     * Zavg - X)
                        Z = np.einsum("mpq,mqkns->mpkns", Bii, zsum)
                        for b in np.where(good)[0]:
                            BZb = np.einsum("p,mpkns->mkns", B[b], Z)
                            Y[b] -= rhok[b][:, None, None, None] * BZb
                        r0all.extend(r0s)
                        r1all.extend(r1s)
                Zs[s] = Z
            # federated averaging (stochastic master :329-351; pmean on a
            # mesh) + dual update X += alpha (Z - Zavg) (slave :867-875)
            Zavg = np.mean(Zs, axis=0)
            feda = 0.0
            for s in range(nslaves):
                d = Zs[s] - Zavg
                Xs[s] += alpha[:, None, None, None, None] * d
                feda += float(np.linalg.norm(d)) ** 2
            if cfg.verbose:
                log(f"FEDA: {nadmm} dual residual="
                    f"{np.sqrt(feda / max(Zavg.size * nslaves, 1)):.6f}")
            res_0 = float(np.mean(r0all))
            res_1 = float(np.mean(r1all))

        for s, rn in enumerate(runners):
            pfreq = states[s]["pfreq"]
            if cfg.use_global_solution:
                for b in range(rn.nsolbw):
                    pfreq[b] = np.einsum("p,mpkns->mkns", Bs[s][b],
                                         Zs[s]).astype(pfreq[b].dtype)
            rn.end_of_tile(tiles[s], ti, states[s], resband[s], res_0,
                           res_1, t0, writer if s == 0 else None,
                           history if s == 0 else [])
    if writer:
        writer.close()
    return history
