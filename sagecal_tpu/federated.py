"""Distributed stochastic calibration with federated averaging.

Capability parity with the reference's stochastic sagecal-mpi mode
(``sagecal-mpi -N > 0``; ``src/MPI/sagecal_stochastic_master.cpp`` +
``sagecal_stochastic_slave.cpp``): every "slave" (here: one subband
dataset; reference: one MPI rank with its MS list) runs minibatch
consensus calibration over its own frequency mini-bands with a LOCAL
polynomial consensus Z, and the slaves are coupled by FEDERATED
AVERAGING of their Z's:

- local Z update (slave :780-825): z = sum_b B_b Y_b (+ alpha Zavg - X
  after the first outer iteration), Z = Bii_fed z where Bii_fed is the
  inverse of (sum_b rho_b B_b B_b^T + alpha I)
  (``find_prod_inverse_full_fed``, consensus_poly.c);
- global Zavg = mean over slaves (stochastic master :329-351) — ONE
  shard_map program over a "slave" mesh axis: every slave's
  epochs x minibatches x bands J/Y/Z updates run shard-local and the
  federated average is a psum (``lax.pmean`` semantics, SURVEY.md P11);
- federated dual X += alpha (Z - Zavg) per cluster (slave :867-875);
- per-(slave, band) J updates are the stochastic consensus LBFGS solver
  (``bfgsfit_minibatch_consensus``), with diverged bands flagged out of
  the Z update exactly as the single-node mode does.

The mesh runner executes one outer (federated) iteration per device
program — the host keeps only the n_admm loop and tile I/O. A
host-sequential implementation (:func:`run_federated_sequential`) is
retained as the oracle for the sharding-invariance test. Slaves that
don't divide the mesh fold onto the local leading axis; a slave count
below the device count pads with masked replicas (admm.pad_subbands
pattern).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from sagecal_tpu import skymodel, utils
from sagecal_tpu.config import RunConfig
from sagecal_tpu.consensus import poly as cpoly
from sagecal_tpu.io import dataset as ds
from sagecal_tpu.solvers import lbfgs as lbfgs_mod
from sagecal_tpu import stochastic as st

RES_RATIO = st.RES_RATIO


def make_fed_outer(rn0, cfg: RunConfig, mesh, nslaves: int, alpha,
                   n_epochs: int):
    """Build the jitted one-outer-iteration federated program.

    Input arrays carry a leading slave axis [Spad, ...] sharded over the
    mesh's "slave" axis (Spad = Fl*ndev; padded slave slots replicate
    slave 0 and are masked out of the federated average):

    data:  x8 [S, nmb, W, B, Fp, 8], wt same, freqs [S, W, Fp],
           u/v/w [S, nmb, B], tslot [nmb, B] (shared), Bb [S, W, P],
           Bii [S, M, P, P], rhok [S, W, M], beam (stacked pytree | None)
    state: p [S, W, M, K, N, 8], mem (stacked LBFGSMemory), Y [S, W, M,
           K, N, 8], Z [S, M, P, K, N, 8], X like Z, Zavg [M, P, K, N,
           8] replicated, it (scalar outer index)

    Returns (p, mem, Y, Z, X, Zavg', resband [S, W], r0h [S, E*nmb, W],
    r1h, feda) — feda is the federated dual residual
    sum_s ||Z_s - Zavg||^2 over real slaves (stochastic master :329-351).
    """
    from sagecal_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    axis = "slave"
    raw = st.make_band_solver(
        rn0.dsky, rn0.n, rn0.cidx, rn0.cmask, rn0.fdelta_chan,
        nu=cfg.robust_nulow, max_lbfgs=cfg.max_lbfgs, consensus=True,
        dobeam=rn0.dobeam, loss=cfg.stochastic_loss).__wrapped__
    minibatches = rn0.minibatches
    alpha_np = np.asarray(alpha)

    def per_slave(x8, wt, freqs, u, v, w, tslot, sta1, sta2, Bb, Bii,
                  rhok, beam, p, mem, Y, Z, X, Zavg, it):
        a5 = jnp.asarray(alpha_np, x8.dtype)[:, None, None, None, None]
        steps = jnp.arange(n_epochs * minibatches) % minibatches

        def body(carry, mb):
            p, mem, Y, Z, resband = carry
            BZ = jnp.einsum("wp,mpkns->wmkns", Bb, Z)
            out = jax.vmap(
                lambda x8b, wtb, fqb, pb, memb, Yb, BZb, rhob: raw(
                    x8b, u[mb], v[mb], w[mb], sta1, sta2, wtb, fqb,
                    tslot[mb], pb, memb, Y=Yb, BZ=BZb, rho=rhob,
                    beam=beam)
            )(x8[mb], wt[mb], freqs, p, mem, Y, BZ, rhok)
            p, mem = out.p, out.mem
            r0s, r1s = out.res_0, out.res_1
            resband = jnp.where((r0s > 0) & (r1s > 0), r1s, jnp.inf)
            rmean = jnp.mean(r1s)
            good = (resband <= RES_RATIO * rmean).astype(p.dtype)
            g5 = good[:, None, None, None, None]
            r4 = rhok[..., None, None, None]
            # local ADMM update (slave :780-825)
            Y = Y + g5 * r4 * p
            zsum = jnp.einsum("w,wp,wmkns->mpkns", good, Bb, Y)
            zsum = zsum + jnp.where(it > 0, a5 * Zavg - X, 0.0)
            Z = jnp.einsum("mpq,mqkns->mpkns", Bii, zsum)
            BZn = jnp.einsum("wp,mpkns->wmkns", Bb, Z)
            Y = Y - g5 * r4 * BZn
            return (p, mem, Y, Z, resband), (r0s, r1s)

        resband0 = jnp.zeros(x8.shape[1], x8.dtype)   # [W] bands
        (p, mem, Y, Z, resband), (r0h, r1h) = jax.lax.scan(
            body, (p, mem, Y, Z, resband0), steps)
        return p, mem, Y, Z, resband, r0h, r1h

    beam_ax = None if rn0.tile_beam is None else 0

    def outer_local(x8, wt, freqs, u, v, w, tslot, sta1, sta2, Bb, Bii,
                    rhok, beam, p, mem, Y, Z, X, Zavg, it):
        Sl = x8.shape[0]
        dev_idx = jax.lax.axis_index(axis)
        smask = ((dev_idx * Sl + jnp.arange(Sl))
                 < nslaves).astype(x8.dtype)
        p, mem, Y, Z, resband, r0h, r1h = jax.vmap(
            per_slave,
            in_axes=(0, 0, 0, 0, 0, 0, None, None, None, 0, 0, 0,
                     beam_ax, 0, 0, 0, 0, 0, None, None),
        )(x8, wt, freqs, u, v, w, tslot, sta1, sta2, Bb, Bii, rhok,
          beam, p, mem, Y, Z, X, Zavg, it)
        s6 = smask[:, None, None, None, None, None]
        # federated averaging = pmean over REAL slaves (P11)
        Zavg_new = jax.lax.psum(jnp.sum(jnp.where(s6 > 0, Z, 0.0),
                                        axis=0), axis) / nslaves
        d = Z - Zavg_new[None]
        X = X + jnp.asarray(alpha_np,
                            X.dtype)[None, :, None, None, None, None] * d
        X = jnp.where(s6 > 0, X, 0.0)
        feda = jax.lax.psum(
            jnp.sum(smask * jnp.sum(d * d, axis=(1, 2, 3, 4, 5))), axis)
        return p, mem, Y, Z, X, Zavg_new, resband, r0h, r1h, feda

    ps, pr = P(axis), P()
    in_specs = ((ps,) * 6 + (pr, pr, pr) + (ps,) * 3
                + ((pr,) if beam_ax is None else (ps,))
                + (ps,) * 5 + (pr, pr))
    out_specs = (ps, ps, ps, ps, ps, pr, ps, ps, ps, pr)
    return jax.jit(shard_map(outer_local, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


def _fed_setup(cfg: RunConfig, paths: list):
    """Shared slave/basis/state setup for both federated implementations
    — the identical-math premise of the sharding-invariance oracle rests
    on both paths consuming exactly this."""
    # each slave path may be a SimMS directory or a real CASA table
    mss = [ds.open_part(p, tilesz=cfg.tile_size,
                        data_column=cfg.input_column,
                        out_column=cfg.output_column) for p in paths]
    meta0 = mss[0].meta
    sky = skymodel.read_sky_cluster(
        cfg.sky_model, cfg.cluster_file, meta0["ra0"], meta0["dec0"],
        float(np.mean([m.meta["freq0"] for m in mss])), cfg.format_3)
    runners = [st._StochasticRunner(cfg, m, sky, log=(lambda *a: None))
               for m in mss]
    rn0 = runners[0]
    M = rn0.M
    ref_f = float(np.mean([m.meta["freq0"] for m in mss]))
    alpha = np.full(M, cfg.federated_alpha)
    arho = np.full(M, cfg.admm_rho)
    if cfg.rho_file:
        arho = skymodel.read_cluster_rho(cfg.rho_file, sky.cluster_ids,
                                         cfg.admm_rho)
    Bs, Biis, rhoks = [], [], []
    for rn in runners:
        fcen = np.array([rn.freqs[c0:c0 + nc].mean()
                         for c0, nc in zip(rn.chanstart, rn.nchan)])
        B = cpoly.setup_polynomials(fcen, ref_f, cfg.n_poly,
                                    cfg.poly_type)
        rhok = np.tile(arho[None, :], (rn.nsolbw, 1))       # [nb, M]
        # federated inverse: +alpha I (find_prod_inverse_full_fed)
        Bii = np.asarray(cpoly.find_prod_inverse(
            jnp.asarray(B), jnp.asarray(rhok.T), alpha=jnp.asarray(alpha)))
        Bs.append(B)
        Biis.append(Bii)
        rhoks.append(rhok)
    states = []
    for rn in runners:
        pinit, pfreq = rn.initial_p()
        mems = [lbfgs_mod.lbfgs_memory_init(rn.nparam, cfg.lbfgs_m,
                                            rn.rdt)
                for _ in range(rn.nsolbw)]
        states.append({"pfreq": pfreq, "mems": mems, "pinit": pinit,
                       "res_prev": None})
    return mss, sky, runners, alpha, Bs, Biis, rhoks, states


def run_federated(cfg: RunConfig, paths: list, log=print, mesh=None):
    """Mesh-parallel federated stochastic calibration: slaves ride a
    "slave" mesh axis, one device program per outer iteration, Zavg via
    psum (P11). ``mesh=None`` builds one over all available devices."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mss, sky, runners, alpha, Bs, Biis, rhoks, states = _fed_setup(
        cfg, paths)
    nslaves = len(mss)
    rn0 = runners[0]
    if mesh is None:
        ndev = min(len(jax.devices()), nslaves)
        mesh = Mesh(np.array(jax.devices()[:ndev]), ("slave",))
    ndev = mesh.devices.size
    spad = -(-max(nslaves, ndev) // ndev) * ndev
    log(f"Federated stochastic calibration: {nslaves} slave datasets "
        f"over {ndev} device(s)"
        + (f" (padded to {spad})" if spad != nslaves else "")
        + f", {cfg.n_epochs} epochs x {rn0.minibatches} minibatches, "
        f"{rn0.nsolbw} mini-bands each, {cfg.n_admm} outer iterations")

    M, kmax, n, Pn = rn0.M, rn0.kmax, rn0.n, cfg.n_poly

    outer = make_fed_outer(rn0, cfg, mesh, nslaves, alpha, cfg.n_epochs)
    sh = NamedSharding(mesh, P("slave"))
    shr = NamedSharding(mesh, P())
    rdt = rn0.rdt

    def pad_s(a):
        a = np.asarray(a)
        if spad == nslaves:
            return a
        return np.concatenate(
            [a, np.broadcast_to(a[:1], (spad - nslaves,) + a.shape[1:])])

    def stage_s(a):
        return jax.device_put(jnp.asarray(pad_s(a), rdt), sh)

    pshape = (M, kmax, n, 8)
    BbS = stage_s(np.stack(Bs))
    BiiS = stage_s(np.stack(Biis))
    rhokS = stage_s(np.stack(rhoks))

    writer = rn0.solution_writer()
    n_tiles = min(m.n_tiles for m in mss)
    start = cfg.skip_timeslots
    stop = n_tiles if not cfg.max_timeslots else min(
        n_tiles, start + cfg.max_timeslots)
    history = []
    for ti in range(start, stop):
        t0 = time.time()
        tiles = [m.read_tile(ti) for m in mss]
        for rn, tile in zip(runners, tiles):
            rn.prepare_tile(tile)

        # stage the tile's data: [S, nmb, ...] stacks of band_inputs_all.
        # sta1/sta2/tslot are staged ONCE and replicated: the mesh
        # program assumes homogeneous row/baseline ordering across
        # slaves and minibatches, so verify it instead of trusting it
        x8_s, wt_s, fq_s, u_s, v_s, w_s = [], [], [], [], [], []
        tslot = sta1 = sta2 = None
        for rn in runners:
            per_mb = [rn.band_inputs_all(nmb)
                      for nmb in range(rn.minibatches)]
            x8_s.append(np.stack([np.asarray(a[0]) for a in per_mb]))
            u_s.append(np.stack([np.asarray(a[1]) for a in per_mb]))
            v_s.append(np.stack([np.asarray(a[2]) for a in per_mb]))
            w_s.append(np.stack([np.asarray(a[3]) for a in per_mb]))
            wt_s.append(np.stack([np.asarray(a[6]) for a in per_mb]))
            fq_s.append(np.asarray(per_mb[0][7]))
            ts = np.stack([np.asarray(a[8]) for a in per_mb])
            s1, s2 = np.asarray(per_mb[0][4]), np.asarray(per_mb[0][5])
            for a in per_mb[1:]:
                if not (np.array_equal(np.asarray(a[4]), s1)
                        and np.array_equal(np.asarray(a[5]), s2)):
                    raise ValueError(
                        f"{rn.ms.path}: baseline ordering differs "
                        f"between minibatches — unsupported by the mesh "
                        f"federated program")
            if sta1 is not None and not (
                    np.array_equal(s1, sta1) and np.array_equal(s2, sta2)
                    and np.array_equal(ts, tslot)):
                raise ValueError(
                    f"{rn.ms.path}: baseline/timeslot layout differs "
                    f"from the first slave dataset — unsupported by the "
                    f"mesh federated program (use "
                    f"run_federated_sequential)")
            sta1, sta2, tslot = s1, s2, ts
        beam_s = None
        if rn0.tile_beam is not None:
            beam_s = jax.tree.map(
                lambda *xs: jax.device_put(
                    jnp.asarray(pad_s(np.stack([np.asarray(x)
                                                for x in xs]))), sh),
                *[rn.tile_beam for rn in runners])

        pS = stage_s(np.stack([np.stack(s["pfreq"]) for s in states]))
        memS = jax.tree.map(
            lambda *xs: jax.device_put(jnp.stack(list(xs)
                                                 + [xs[0]] * (spad - nslaves)),
                                       sh),
            *[jax.tree.map(lambda *bs: jnp.stack(bs), *s["mems"])
              for s in states])
        YS = stage_s(np.zeros((nslaves, rn0.nsolbw) + pshape))
        ZS = stage_s(np.zeros((nslaves, M, Pn, kmax, n, 8)))
        XS = stage_s(np.zeros((nslaves, M, Pn, kmax, n, 8)))
        Zavg = jax.device_put(jnp.zeros((M, Pn, kmax, n, 8), rdt), shr)

        data_dev = (stage_s(np.stack(x8_s)), stage_s(np.stack(wt_s)),
                    stage_s(np.stack(fq_s)), stage_s(np.stack(u_s)),
                    stage_s(np.stack(v_s)), stage_s(np.stack(w_s)),
                    jax.device_put(jnp.asarray(tslot), shr),
                    jax.device_put(jnp.asarray(sta1), shr),
                    jax.device_put(jnp.asarray(sta2), shr),
                    BbS, BiiS, rhokS, beam_s)

        res_0 = res_1 = 0.0
        r0h = r1h = None
        for nadmm in range(cfg.n_admm):
            out = outer(*data_dev, pS, memS, YS, ZS, XS, Zavg,
                        jnp.asarray(nadmm, jnp.int32))
            pS, memS, YS, ZS, XS, Zavg, resbandS, r0h, r1h, feda = out
            if cfg.verbose:
                log(f"FEDA: {nadmm} dual residual="
                    f"{float(np.sqrt(np.asarray(feda) / max(Zavg.size * nslaves, 1))):.6f}")
        r0h = np.asarray(r0h)[:nslaves]
        r1h = np.asarray(r1h)[:nslaves]
        res_0, res_1 = float(r0h.mean()), float(r1h.mean())
        resband_np = np.asarray(resbandS)[:nslaves]
        Z_np = np.asarray(ZS)[:nslaves]
        p_np = np.asarray(pS)[:nslaves]
        mem_host = jax.tree.map(np.asarray, memS)

        for s, rn in enumerate(runners):
            pfreq, mems = states[s]["pfreq"], states[s]["mems"]
            for b in range(rn.nsolbw):
                pfreq[b] = p_np[s, b]
                mems[b] = jax.tree.map(lambda a: jnp.asarray(a[s, b]),
                                       mem_host)
            if cfg.use_global_solution:
                for b in range(rn.nsolbw):
                    pfreq[b] = np.einsum("p,mpkns->mkns", Bs[s][b],
                                         Z_np[s]).astype(pfreq[b].dtype)
            rn.end_of_tile(tiles[s], ti, states[s], resband_np[s], res_0,
                           res_1, t0, writer if s == 0 else None,
                           history if s == 0 else [])
    if writer:
        writer.close()
    return history


def run_federated_sequential(cfg: RunConfig, paths: list, log=print):
    """Host-sequential federated implementation: identical math, one
    slave at a time (the sharding-invariance oracle)."""
    mss, sky, runners, alpha, Bs, Biis, rhoks, states = _fed_setup(
        cfg, paths)
    nslaves = len(mss)
    rn0 = runners[0]
    log(f"Federated stochastic calibration: {nslaves} slave datasets, "
        f"{cfg.n_epochs} epochs x {rn0.minibatches} minibatches, "
        f"{rn0.nsolbw} mini-bands each, {cfg.n_admm} outer iterations")

    solver = st.make_band_solver(
        rn0.dsky, rn0.n, rn0.cidx, rn0.cmask, rn0.fdelta_chan,
        nu=cfg.robust_nulow, max_lbfgs=cfg.max_lbfgs, consensus=True,
        dobeam=rn0.dobeam, loss=cfg.stochastic_loss)

    P = cfg.n_poly
    M, kmax, n = rn0.M, rn0.kmax, rn0.n
    pshape = (M, kmax, n, 8)
    writer = rn0.solution_writer()
    n_tiles = min(m.n_tiles for m in mss)
    start = cfg.skip_timeslots           # -K (CTRL_SKIP, master :623-634)
    stop = n_tiles if not cfg.max_timeslots else min(
        n_tiles, start + cfg.max_timeslots)
    history = []
    for ti in range(start, stop):
        t0 = time.time()
        tiles = [m.read_tile(ti) for m in mss]
        for rn, tile in zip(runners, tiles):
            rn.prepare_tile(tile)
        Zavg = np.zeros((M, P, kmax, n, 8))
        Zs = [np.zeros_like(Zavg) for _ in range(nslaves)]
        Xs = [np.zeros_like(Zavg) for _ in range(nslaves)]
        Ys = [np.zeros((rn.nsolbw,) + pshape) for rn in runners]
        resband = [np.zeros(rn.nsolbw) for rn in runners]
        res_0 = res_1 = 0.0
        for nadmm in range(cfg.n_admm):
            r0all, r1all = [], []
            for s, rn in enumerate(runners):
                B, Bii, rhok = Bs[s], Biis[s], rhoks[s]
                Y, Z, X = Ys[s], Zs[s], Xs[s]
                pfreq, mems = states[s]["pfreq"], states[s]["mems"]
                for nepch in range(cfg.n_epochs):
                    for nmb in range(rn.minibatches):
                        r0s, r1s = [], []
                        for b in range(rn.nsolbw):
                            BZ = np.einsum("p,mpkns->mkns", B[b], Z)
                            args = rn.band_inputs(nmb, b)
                            out = solver(
                                *args, jnp.asarray(pfreq[b], rn.rdt),
                                mems[b], Y=jnp.asarray(Y[b], rn.rdt),
                                BZ=jnp.asarray(BZ, rn.rdt),
                                rho=jnp.asarray(rhok[b], rn.rdt),
                                beam=rn.tile_beam)
                            pfreq[b] = np.asarray(out.p)
                            mems[b] = out.mem
                            r00, r01 = float(out.res_0), float(out.res_1)
                            resband[s][b] = r01 if (r00 > 0 and r01 > 0) \
                                else np.inf
                            r0s.append(r00)
                            r1s.append(r01)
                        rmean = float(np.mean(r1s))
                        fband = resband[s] > RES_RATIO * rmean
                        good = ~fband
                        # local ADMM update (slave :780-825)
                        for b in np.where(good)[0]:
                            Y[b] += (rhok[b][:, None, None, None]
                                     * pfreq[b])
                        zsum = np.einsum("b,bp,bmkns->mpkns",
                                         good.astype(float), B, Y)
                        if nadmm > 0:
                            zsum += (alpha[:, None, None, None, None]
                                     * Zavg - X)
                        Z = np.einsum("mpq,mqkns->mpkns", Bii, zsum)
                        for b in np.where(good)[0]:
                            BZb = np.einsum("p,mpkns->mkns", B[b], Z)
                            Y[b] -= rhok[b][:, None, None, None] * BZb
                        r0all.extend(r0s)
                        r1all.extend(r1s)
                Zs[s] = Z
            # federated averaging (stochastic master :329-351; pmean on a
            # mesh) + dual update X += alpha (Z - Zavg) (slave :867-875)
            Zavg = np.mean(Zs, axis=0)
            feda = 0.0
            for s in range(nslaves):
                d = Zs[s] - Zavg
                Xs[s] += alpha[:, None, None, None, None] * d
                feda += float(np.linalg.norm(d)) ** 2
            if cfg.verbose:
                log(f"FEDA: {nadmm} dual residual="
                    f"{np.sqrt(feda / max(Zavg.size * nslaves, 1)):.6f}")
            res_0 = float(np.mean(r0all))
            res_1 = float(np.mean(r1all))

        for s, rn in enumerate(runners):
            pfreq = states[s]["pfreq"]
            if cfg.use_global_solution:
                for b in range(rn.nsolbw):
                    pfreq[b] = np.einsum("p,mpkns->mkns", Bs[s][b],
                                         Zs[s]).astype(pfreq[b].dtype)
            rn.end_of_tile(tiles[s], ti, states[s], resband[s], res_0,
                           res_1, t0, writer if s == 0 else None,
                           history if s == 0 else [])
    if writer:
        writer.close()
    return history
