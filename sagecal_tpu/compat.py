"""Small cross-version jax shims.

The codebase targets current jax spellings; containers pinned to older
jaxlibs (0.4.x) get the equivalent older entry points here so a version
skew never takes out whole subsystems (seed failure: ``from jax import
shard_map`` killed every consensus/federated test on jax 0.4.37).
"""

from __future__ import annotations


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices. jax >= 0.5 spells this as the
    ``jax_num_cpu_devices`` config option; older versions only honor
    the XLA_FLAGS route, which must land before the backend
    initializes (both CLIs call this before first device use)."""
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        import os
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword set; falls back to
    ``jax.experimental.shard_map.shard_map`` (jax < 0.6), where the
    replication-check keyword is spelled ``check_rep``.

    Multi-axis meshes (the 2-D ``('freq', 'time')`` consensus mesh,
    admm.make_admm_runner_2d) work on BOTH spellings — the
    experimental entry point has carried multi-axis support since jax
    0.4.3, verified on 0.4.37 by tests/test_mesh2d.py. A jax too old
    to have either entry point gets a clear capability error naming
    the version floor instead of an import failure (or, worse, a
    shape error deep inside tracing) at first mesh use."""
    try:
        from jax import shard_map as sm
    except ImportError:
        try:
            from jax.experimental.shard_map import shard_map as sm
        except ImportError as e:
            import jax
            axes = tuple(getattr(mesh, "axis_names", ()) or ())
            what = (f"a {len(axes)}-D mesh {axes}" if len(axes) > 1
                    else f"mesh {axes}")
            raise RuntimeError(
                f"shard_map over {what} requires jax >= 0.4.3 "
                f"(jax.experimental.shard_map) or jax >= 0.5 "
                f"(jax.shard_map); this is jax {jax.__version__} with "
                f"neither entry point") from e
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_vma=check_vma)
