"""Bytes-accounting roofline: which hardware limit is each hot path on?

Round-5 VERDICT rejected MFU as the reported axis: 2x2-Jones calibration
does tiny matmuls, so "% of bf16 matmul peak" is structurally ~0 and
says nothing about whether a program is fast. The right question is the
roofline one — per compiled program, how many FLOPs and how many HBM
bytes does one execution touch (XLA's own cost analysis via
``lowered.compile().cost_analysis()``), what does measured wall-clock
make of that in achieved GFLOP/s and GB/s, and which side of the device
ridge point (peak FLOP/s ÷ peak bytes/s) does the program's operational
intensity fall on. Both CubiCal (arXiv:1805.03410) and the SAGECal GPU
work (arXiv:1910.13908) ground their speedup claims in exactly this
per-kernel op/byte accounting.

Known slack, inherited from XLA's static analysis: loop bodies are
priced once regardless of trip count (callers add the dynamic-trip
correction — see bench.py's trip-accounting block), and "bytes accessed"
is the optimistic each-buffer-moves-once figure, so achieved GB/s is a
lower bound on real traffic.
"""

from __future__ import annotations

import numpy as np

# Per-chip peaks by device kind substring: (bf16 peak FLOP/s, HBM
# bytes/s). Sources: published TPU spec sheets (v2 45 TF/700 GB/s,
# v3 123 TF/900 GB/s, v4 275 TF/1228 GB/s, v5e 197 TF/819 GB/s,
# v5p 459 TF/2765 GB/s, v6e 918 TF/1640 GB/s). Order matters: "v5p"
# must match before "v5".
_PEAKS = (
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)

# Nominal single-core host fallback so the CPU bench still classifies:
# ~one AVX2 core (16 f32 FLOP/cycle x ~3 GHz) against ~25 GB/s of the
# socket's memory bandwidth. Coarse on purpose — the *ridge* (~2
# FLOP/byte) is what the bound verdict needs, and CPU ridges sit within
# a small factor of it across a decade of hardware.
_CPU_PEAKS = (1e11, 25e9)


def device_peaks(device):
    """(peak FLOP/s, peak bytes/s, nominal?) for ``device``; Nones when
    the device kind is unrecognized."""
    if getattr(device, "platform", None) == "cpu":
        return _CPU_PEAKS[0], _CPU_PEAKS[1], True
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, pf, pb in _PEAKS:
        if key in kind:
            return pf, pb, False
    return None, None, False


def peak_flops(device):
    """bf16 peak FLOP/s (the legacy MFU denominator); None if unknown."""
    pf, _, nominal = device_peaks(device)
    return None if nominal else pf


# ---------------------------------------------------------------------------
# per-program cost extraction
# ---------------------------------------------------------------------------

def zero_cost() -> dict:
    return {"flops": 0.0, "bytes_accessed": 0.0}


def _from_cost_analysis(ca) -> dict:
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def pallas_cost(jfn, args, kwargs=None) -> dict:
    """{flops, bytes_accessed} summed over the COMPILED pallas_call
    equations in ``jfn``'s jaxpr.

    XLA's cost analysis cannot see inside a Mosaic-compiled
    ``pallas_call`` — on TPU the kernel lowers to an opaque custom call
    priced at ~zero, silently dropping the fused sweep's traffic from
    every per-trip figure. This walks the (pre-lowering) jaxpr instead:
    each pallas_call carries its author's ``cost_estimate``
    (ops/sweep_pallas.py provides one; absent that, bytes fall back to
    the operand+result aval sizes — the same each-buffer-moves-once
    convention as XLA's own figure, with flops unknown = 0).
    INTERPRET-mode calls are skipped: the interpreter lowering is plain
    HLO, which cost_analysis already prices — adding the estimate there
    would double-count (so CPU-banked rounds stay consistent)."""
    import jax
    out = zero_cost()

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                if eqn.params.get("interpret"):
                    continue
                ce = eqn.params.get("cost_estimate")
                if ce is not None and (getattr(ce, "flops", 0)
                                       or getattr(ce, "bytes_accessed",
                                                  0)):
                    out["flops"] += float(ce.flops)
                    out["bytes_accessed"] += float(ce.bytes_accessed)
                else:
                    out["bytes_accessed"] += float(sum(
                        v.aval.size * v.aval.dtype.itemsize
                        for v in list(eqn.invars) + list(eqn.outvars)
                        if hasattr(v, "aval")))
            for v in eqn.params.values():
                # sub-jaxprs hide in several param shapes: a bare
                # ClosedJaxpr (pjit/scan/while), an object with .eqns,
                # or a TUPLE of ClosedJaxprs (lax.cond/switch
                # 'branches') — missing the tuple case would silently
                # drop any kernel sitting under a solver-mode cond
                for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                    if isinstance(sub, jax.core.ClosedJaxpr):
                        walk(sub.jaxpr)
                    elif hasattr(sub, "eqns"):
                        walk(sub)

    try:
        walk(jax.make_jaxpr(jfn)(*args, **(kwargs or {})).jaxpr)
    except Exception:           # pricing must never break a bench run
        pass
    return out


def program_cost(jfn, args, kwargs=None) -> dict:
    """FLOPs + bytes accessed of ONE execution of the compiled program
    ``jfn(*args, **kwargs)`` via XLA cost analysis, plus the
    :func:`pallas_cost` correction for Mosaic-compiled kernels the
    analysis cannot see into. Static figures: loop bodies price once
    (callers correct with executed trip counts)."""
    comp = jfn.lower(*args, **(kwargs or {})).compile()
    cost = _from_cost_analysis(comp.cost_analysis())
    return combine(cost, pallas_cost(jfn, args, kwargs))


def lower_cost(fn, *specs) -> dict:
    """Price ``fn`` at abstract shapes (jax.ShapeDtypeStruct) — lowering
    + cost analysis only, nothing executes."""
    import jax
    return program_cost(jax.jit(fn), specs, {})


def combine(*costs) -> dict:
    """Field-wise sum; None entries are skipped."""
    out = zero_cost()
    for c in costs:
        if c is None:
            continue
        out["flops"] += c["flops"]
        out["bytes_accessed"] += c["bytes_accessed"]
    return out


def scale(cost, k) -> dict:
    if cost is None:
        return None
    return {"flops": cost["flops"] * k,
            "bytes_accessed": cost["bytes_accessed"] * k}


def trip_correct(cost, per_trip, trips) -> dict:
    """Dynamic-trip correction: ``cost`` + ``trips`` x ``per_trip``.

    XLA cost analysis prices loop bodies ONCE regardless of trip count,
    so per-program figures undercount iterative solvers by orders of
    magnitude. Callers price one body trip (:func:`lower_cost` at the
    solve shapes) and multiply by the solver's EXECUTED iteration
    counter. Two counter families exist: outer damping/TR/LBFGS trips
    (``info["solver_iters"]``/``info["lbfgs_iters"]``) and — under the
    matrix-free ``inner="cg"`` path — the PCG inner trips
    (``info["cg_iters"]``), each priced as one gn_matvec +
    preconditioner application; pricing the damping trip alone would
    hide the Krylov traffic the inexact-Newton path actually moves.
    ``per_trip=None`` (pricing unavailable) returns ``cost`` unchanged
    rather than silently zeroing the base figure.

    Pallas note: per-trip prices that contain a Mosaic-compiled
    ``pallas_call`` must come from :func:`program_cost`/
    :func:`lower_cost` (which fold in :func:`pallas_cost`) — raw
    cost_analysis figures silently drop the kernel's bytes/FLOPs, and
    multiplying a dropped cost by the trip count here would compound
    the hole."""
    if cost is None or per_trip is None:
        return cost
    return combine(cost, scale(per_trip, trips))


def nbytes_of(tree) -> int:
    """Total host bytes of every array leaf in a pytree — the staging
    accountant (how much crosses host->device per tile)."""
    import jax
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree.leaves(tree)))


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def roofline_fields(cost, wall_s, device) -> dict:
    """Roofline record for one timed step: achieved rates + bound verdict.

    ``cost``: {"flops", "bytes_accessed"} of the step (trip-corrected by
    the caller); ``wall_s``: measured seconds per step. Returns a dict
    ready to merge into a bench record:

    - ``flops``, ``bytes_accessed`` — the step's totals;
    - ``achieved_flops_per_s``, ``achieved_gbps`` — vs wall-clock;
    - ``intensity`` — FLOPs per byte accessed;
    - ``ridge`` — the device's peak-FLOPs/peak-bandwidth ridge point;
    - ``bound`` — "compute" | "bandwidth": which roof the program's
      intensity puts it under (below the ridge = bandwidth-bound);
    - ``pct_peak_flops`` / ``pct_peak_bw`` — achieved fraction of each
      roof (absent when device peaks are unknown);
    - ``peaks_nominal`` — True when the CPU fallback peaks were used.
    """
    flops = float(cost["flops"])
    bts = float(cost["bytes_accessed"])
    out = {"flops": flops, "bytes_accessed": bts}
    if wall_s and wall_s > 0:
        out["achieved_flops_per_s"] = flops / wall_s
        out["achieved_gbps"] = bts / wall_s / 1e9
    intensity = flops / bts if bts > 0 else float("inf")
    out["intensity"] = intensity if np.isfinite(intensity) else None
    pf, pb, nominal = device_peaks(device)
    if pf and pb:
        ridge = pf / pb
        out["ridge"] = ridge
        out["bound"] = "bandwidth" if intensity < ridge else "compute"
        out["peaks_nominal"] = bool(nominal)
        if wall_s and wall_s > 0:
            out["pct_peak_flops"] = 100.0 * flops / wall_s / pf
            out["pct_peak_bw"] = 100.0 * bts / wall_s / pb
    else:
        # no peak table for this device: classify against the observed
        # machine balance so 'bound' is always present — a program doing
        # >100 FLOPs per byte is compute-bound on any current hardware
        out["bound"] = "compute" if intensity >= 100.0 else "bandwidth"
    return out
