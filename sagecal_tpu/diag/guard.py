"""Compilation-count guard: prove telemetry adds zero retraces.

``jax.monitoring`` fires an event per XLA compile request; a
process-lifetime listener counts them. Tests (and careful perf work)
snapshot the counter around a workload twice — diag off, then diag on —
and assert the deltas match: the tracing hooks are host-side emits, so
any difference means a hook leaked into a traced program.

The listener is installed lazily on first use and never removed (jax
exposes no unregister); it is one integer increment per compile, which
is noise next to the compile itself.
"""

from __future__ import annotations

_STATE = {"installed": False, "count": 0}

# one event per compile request across jax versions >= 0.4.x; keep as a
# tuple so a rename can be tracked by adding the new name
_COMPILE_EVENTS = ("/jax/compilation_cache/compile_requests_use_cache",)


def _listener(event, **kwargs):
    if event in _COMPILE_EVENTS:
        _STATE["count"] += 1


def install() -> None:
    if _STATE["installed"]:
        return
    import jax.monitoring
    jax.monitoring.register_event_listener(_listener)
    _STATE["installed"] = True


def compile_count() -> int:
    """Compile requests observed since :func:`install` (auto-installs)."""
    install()
    return _STATE["count"]


class CompileGuard:
    """Context manager: ``with CompileGuard() as g: ...; g.compiles``."""

    def __enter__(self):
        install()
        self._c0 = _STATE["count"]
        return self

    def __exit__(self, *exc):
        self.compiles = _STATE["count"] - self._c0
        return False
