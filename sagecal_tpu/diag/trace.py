"""Structured JSONL tracing: phase timers + convergence records.

Zero-dependency by design (stdlib only — no jax, no numpy): the solver
and pipeline layers import this module unconditionally, and an import
that pulled in jax from inside ``sagecal_tpu.solvers.sage`` would be a
layering inversion. Emitters call :func:`emit`/:func:`phase` freely;
until :func:`enable` installs a live :class:`Tracer` both are no-ops
costing one attribute load and one ``is None`` test.

File format: one JSON object per line. Every record carries

- ``t``   — unix epoch seconds (float) at emit time,
- ``ev``  — the event name (str),

plus event-specific fields. The emitting sites keep a small stable
vocabulary so downstream tooling can rely on it:

===============  ============================================================
event            meaning / required extra fields
===============  ============================================================
``run_start``    first record; run metadata (argv, entry point)
``phase``        a timed host phase: ``name`` (io/stage/solve/residual/
                 write/read/consensus/arrival_wait), ``dur_s``;
                 optional ``tile``, ``bg`` (True when the phase ran on
                 a background prefetch/writeback thread — under
                 overlapped execution the "io" phase records the
                 host's WAIT for the next tile, the bubble, while the
                 thread's own read/stage time carries ``bg``).
                 ``arrival_wait`` is time spent waiting for a tile to
                 ARRIVE (ingest pacing or a live stream transport,
                 sched.Prefetcher) — the tenant's data rate, NEVER
                 counted as io/bubble; producer-side waits carry
                 ``bg``, the consumer's overlapping block does not
``em_sweep``     one SAGE EM sweep (solvers/sage.py host driver):
                 ``sweep``, ``wall_s``, ``fused``, ``err_reduction``,
                 ``solver_iters`` (cumulative executed inner trips)
``tile``         one solve interval's convergence summary (pipeline.py /
                 cli_mpi.py): ``tile``, ``res_0``, ``res_1``; optional
                 ``mean_nu``, ``solver_iters``, ``lbfgs_iters``,
                 ``minutes``, ``primal``, ``rho_mean``, and the
                 overlap accounting pair ``bubble_s`` (host seconds
                 blocked on data movement for this tile: io wait +
                 write wait/backpressure) / ``overlap`` (the prefetch
                 depth; 0 = synchronous reference loop)
``admm_iter``    one consensus-ADMM iteration: ``iter``, ``r1_mean``,
                 ``dual``; optional ``interval``, ``rho_mean``,
                 ``primal``, ``deferred`` (True when the record was
                 emitted in one batched fetch AFTER the host loop —
                 the overlap-preserving path: no per-iteration sync)
``minibatch``    one stochastic minibatch solve: ``epoch``, ``minibatch``,
                 ``res_0``, ``res_1``; optional ``admm``, ``iters``
``stage_bytes``  host->device staging accounting: ``bytes``, ``what``;
                 optional ``tile``
``run_end``      last record; ``wall_s`` for the whole run
===============  ============================================================

Values must be JSON-serializable scalars/strings (callers convert device
arrays with ``float(...)``/``int(...)`` *after* checking :func:`active`,
so the disabled path never forces a device sync).
"""

from __future__ import annotations

import json
import threading
import time

from sagecal_tpu.analysis import threadsan

# record fields guaranteed on every line (the schema tests key on this)
REQUIRED_FIELDS = ("t", "ev")

_TRACER = None          # module-level singleton; None = disabled

# thread-scoped tracer override (serve: per-job --diag routing). The
# server runs many jobs through one process; each job's records go to
# its OWN trace file. A scope installed on a thread (the device-owner
# thread around a job's step, the job's reader thread, its writer-
# thread jobs) routes that thread's emits to the job tracer; threads
# without a scope keep the process tracer. Stored as a stack so scopes
# nest (a server-level tracer can wrap a job-level one).
#
# CONTRACT (metrics-era, tests/test_diag.py pins it): scope stacks are
# STRICTLY thread-local. Entering a scope on thread A changes nothing
# about thread B's routing — not even when B was spawned by A while
# the scope was live (threading.local starts empty per thread; a new
# thread that must attribute to a job enters the job's own scope via
# the sched context= / trace_ctx= factories, see
# serve.scheduler.job_telemetry_ctx). obs.metrics.scope_labels keeps
# the identical stack semantics, so a metric emitted inside a scoped
# thread attributes to the owning job exactly when a trace record
# routed there would.
_SCOPED = threading.local()


def _current():
    st = getattr(_SCOPED, "stack", None)
    return st[-1] if st else _TRACER


class _Scope:
    __slots__ = ("_t",)

    def __init__(self, tracer):
        self._t = tracer

    def __enter__(self):
        st = getattr(_SCOPED, "stack", None)
        if st is None:
            st = _SCOPED.stack = []
        st.append(self._t)
        return self._t

    def __exit__(self, *exc):
        _SCOPED.stack.pop()
        return False


def scope(tracer):
    """Route THIS thread's emits to ``tracer`` while the context is
    live (``None`` silences them). Per-job trace routing for the serve
    scheduler; nests, and never touches other threads."""
    return _Scope(tracer)


class Tracer:
    """Append-only JSONL event writer with monotonic phase timers."""

    def __init__(self, path, **run_meta):
        self.path = path
        self._f = open(path, "a", buffering=1)   # line-buffered
        # overlapped execution (sagecal_tpu.sched) emits from the
        # prefetch and writer threads concurrently with the main loop;
        # TextIOWrapper.write is not thread-safe, so one lock keeps
        # every JSONL line atomic
        self._lock = threadsan.make_lock("Tracer._lock")
        self._t0 = time.time()
        self.emit("run_start", **run_meta)

    def emit(self, ev: str, **fields) -> None:
        rec = {"t": time.time(), "ev": ev}
        rec.update(fields)
        try:
            line = json.dumps(rec) + "\n"
        except (TypeError, ValueError):
            # a non-serializable field must not kill a calibration run;
            # keep the record with offenders stringified
            rec = {k: (v if isinstance(v, (int, float, str, bool,
                                           type(None))) else repr(v))
                   for k, v in rec.items()}
            line = json.dumps(rec) + "\n"
        with self._lock:
            self._f.write(line)

    def phase(self, name: str, **fields):
        return _Phase(self, name, fields)

    def close(self) -> None:
        if self._f.closed:
            return
        self.emit("run_end", wall_s=time.time() - self._t0)
        self._f.close()


class _Phase:
    """Context manager timing one host phase; emits on exit."""

    __slots__ = ("_tr", "_name", "_fields", "_t0")

    def __init__(self, tracer, name, fields):
        self._tr = tracer
        self._name = name
        self._fields = fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tr.emit("phase", name=self._name,
                      dur_s=time.perf_counter() - self._t0, **self._fields)
        return False


class _NullPhase:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def enable(path, **run_meta) -> Tracer:
    """Open ``path`` for appending and make it the process tracer."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
    _TRACER = Tracer(path, **run_meta)
    return _TRACER


def disable() -> None:
    """Close and uninstall the process tracer (no-op when disabled)."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
        _TRACER = None


def get() -> Tracer | None:
    return _current()


def active() -> bool:
    """True when a tracer is installed (process-wide or scoped onto
    this thread). Emitting sites whose field conversion is itself
    costly (device->host syncs) gate on this."""
    return _current() is not None


def emit(ev: str, **fields) -> None:
    """Module-level emit: one line when enabled, no-op otherwise."""
    t = _current()
    if t is not None:
        t.emit(ev, **fields)


def phase(name: str, **fields):
    """Module-level phase timer; a shared null context when disabled."""
    t = _current()
    if t is None:
        return _NULL_PHASE
    return t.phase(name, **fields)


def overlap_stats(recs: list) -> dict:
    """Pipeline-bubble accounting over one run's records.

    Classifies host wall-clock into device-driving time (solve +
    residual dispatch phases) vs bubble (host blocked on data
    movement): per-tile ``bubble_s`` when the tile records carry the
    overlap fields, else the synchronous attribution io + write +
    residual phase sums. Background (``bg``) phase records are the
    prefetch/writeback threads' own time and never count as bubble.

    Arrival waits (the ``arrival_wait`` phase — ingest pacing / live
    stream transports) are the TENANT'S data rate, not a pipeline
    bubble: they are summed separately into ``arrival_wait_s`` and
    excluded from both busy and bubble.

    Returns ``{"tiles", "wall_s", "busy_s", "bubble_s",
    "arrival_wait_s", "busy_frac", "bubble_frac", "overlap"}`` —
    fractions are of ``wall_s`` (run_end when present, else the
    record time span).
    """
    tiles = [r for r in recs if r.get("ev") == "tile"]
    phases = [r for r in recs if r.get("ev") == "phase"
              and not r.get("bg")]
    wall = None
    for r in recs:
        if r.get("ev") == "run_end" and "wall_s" in r:
            wall = float(r["wall_s"])
    if wall is None and recs:
        wall = float(recs[-1]["t"]) - float(recs[0]["t"])
    busy = sum(r.get("dur_s", 0.0) for r in phases
               if r.get("name") in ("solve", "residual"))
    overlap = max([int(r.get("overlap", 0)) for r in tiles], default=0)
    if any("bubble_s" in r for r in tiles):
        bubble = sum(float(r.get("bubble_s", 0.0)) for r in tiles)
    else:
        # sync attribution: io (inline read) + write (blocking fetch +
        # disk) are the host's data-movement stalls
        bubble = sum(r.get("dur_s", 0.0) for r in phases
                     if r.get("name") in ("io", "write"))
    arrival = sum(r.get("dur_s", 0.0) for r in phases
                  if r.get("name") == "arrival_wait")
    wall = wall or 0.0
    return {
        "tiles": len(tiles), "wall_s": wall, "busy_s": busy,
        "bubble_s": bubble, "arrival_wait_s": arrival,
        "overlap": overlap,
        "busy_frac": (busy / wall) if wall else 0.0,
        "bubble_frac": (bubble / wall) if wall else 0.0,
    }


def read(path) -> list:
    """Parse a trace file back into a list of records (for tests and
    post-run analysis). Raises ValueError on a malformed line or a
    record missing the required fields."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: malformed JSONL: {e}")
            for k in REQUIRED_FIELDS:
                if k not in rec:
                    raise ValueError(
                        f"{path}:{i + 1}: record missing '{k}': {rec}")
            out.append(rec)
    return out
