"""``sagecal_tpu.diag`` — runtime telemetry, bytes-accounting roofline,
and convergence tracing.

Three small modules, layered so the hot paths stay clean:

- :mod:`sagecal_tpu.diag.trace` — a zero-dependency (stdlib-only) JSONL
  event emitter with phase timers and per-iteration convergence records.
  The application/solver layers call ``trace.emit(...)`` /
  ``trace.phase(...)`` unconditionally; both are cheap no-ops until a
  CLI (or a test) calls ``trace.enable(path)``. Nothing here touches
  jax, so importing it from the solver layer costs nothing and cannot
  retrace a program.
- :mod:`sagecal_tpu.diag.roofline` — FLOPs and bytes-accessed
  extraction from XLA's per-program cost analysis
  (``lowered.compile().cost_analysis()``), combined with measured
  wall-clock into achieved GFLOP/s + GB/s and a compute- vs
  bandwidth-bound verdict against device peaks. This replaces MFU as
  the reported axis (round-5 VERDICT: "MFU is the wrong roofline axis
  for this workload").
- :mod:`sagecal_tpu.diag.guard` — a jit-compilation counter (via
  ``jax.monitoring``) so tests can assert that telemetry-off — and
  telemetry-on — add zero retraces.
"""

from sagecal_tpu.diag import trace  # noqa: F401  (zero-dep, always safe)

__all__ = ["trace"]
