"""Distributed consensus-ADMM across frequency subbands on a device mesh.

Capability parity with the reference's MPI master/slave per-timeslot loop
(``src/MPI/sagecal_master.cpp:621-890`` + ``sagecal_slave.cpp:488-930``,
SURVEY.md section 3.3), re-architected as ONE SPMD program over a
``jax.sharding.Mesh`` with a "freq" axis (SURVEY.md P9/P10/C1):

- the hub-and-spoke MPI tag protocol disappears: J/Y updates run
  shard-local per subband; the master's gather(Y) + Z-solve + broadcast(BZ)
  becomes ``psum`` over the subband axis + a replicated small solve;
- ADMM iteration 0: plain SAGE solve, dual seed Y = rho*J, then manifold
  averaging of Y across frequency (master :739-751) — here a psum-based
  Procrustes averaging (consensus/manifold.py);
- iterations k>0: augmented-Lagrangian SAGE solve (admm_solve.c:221
  semantics via solvers.sage with the admm term), Y += rho*J, z-sum via
  psum, Z = Bii z, Y -= rho*BZ (slave :686-770);
- optional Barzilai-Borwein adaptive rho per (subband, cluster)
  (slave :782-786, consensus_poly.c:923);
- rho is scaled by each subband's unflagged-data fraction
  (master :646-650).

Data multiplexing (Scurrent rotation, master :883-889) is unnecessary when
every subband owns a shard; when F exceeds the mesh size, multiple subbands
ride one shard via the local leading axis — same effect, no rotation.

When F does not divide the mesh size, the caller pads the subband axis up
to ``Fl * ndev`` (replicating a real subband's data so padded solves stay
numerically tame) and passes the REAL count as ``nf_total``: rows with
global index >= nf_total get zero basis rows in the padded ``B_poly``,
zero rho, and are masked out of the manifold mean and every dual/Y
quantity — so 7 subbands use 8 devices instead of shrinking the mesh to a
divisor (the reference's analogue is idle slaves, sagecal_master.cpp:155).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sagecal_tpu.consensus import manifold as mf
from sagecal_tpu.consensus import poly as cpoly
from sagecal_tpu.diag import trace as dtrace
from sagecal_tpu.obs import metrics as obs
from sagecal_tpu.solvers import normal_eq as ne
from sagecal_tpu.solvers import sage


class ADMMConfig(NamedTuple):
    n_admm: int = 10
    npoly: int = 2
    poly_type: int = 2
    rho: float = 5.0             # scalar, or [M] per-cluster array (-G file)
    adaptive_rho: bool = False
    manifold_iters: int = 20     # master :740 Niter
    sage: sage.SageConfig = sage.SageConfig()
    # -X l2,l1,order,fista_iters,cadence (README.md:160-166); None = off
    spatialreg: tuple | None = None
    federated_alpha: float = 0.0  # -u : alpha of the spatial/federated prior


def pad_subbands(arrays, B_poly, nf: int, ndev: int):
    """THE padding contract for uneven F over the mesh, in one place.

    arrays: sequence of host arrays with a leading real-subband axis
    [nf, ...]. Returns (padded_arrays, padded_B, fpad): each array's
    leading axis padded to ``fpad = ceil(nf/ndev)*ndev`` (ndev may exceed
    nf: fpad then equals ndev) by replicating the first subband — padded
    solves stay numerically tame — and B_poly gains zero rows so padded
    slots contribute nothing to any collective. Pass the REAL count nf as
    ``nf_total`` to :func:`make_admm_runner`; slice every per-subband
    output back to [:nf] on the host.
    """
    ndev = max(int(ndev), 1)
    fpad = -(-max(nf, ndev) // ndev) * ndev
    if fpad == nf:
        return list(arrays), np.asarray(B_poly), fpad
    out = []
    for a in arrays:
        a = np.asarray(a)
        out.append(np.concatenate(
            [a, np.broadcast_to(a[:1], (fpad - nf,) + a.shape[1:])]))
    B = np.asarray(B_poly)
    B = np.vstack([B, np.zeros((fpad - nf, B.shape[1]), B.dtype)])
    return out, B, fpad


def _blocks(J_r8):
    """[.., M, K, N, 8] real Jones -> [.., M*K, 2N, 2] complex blocks."""
    J = ne.jones_r2c(J_r8)
    shp = J.shape
    J = J.reshape(shp[:-5] + (shp[-5] * shp[-4], shp[-3], 2, 2))
    return mf.jones_to_blocks(J)


def _unblocks(X, m, k, n):
    J = mf.blocks_to_jones(X)
    J = J.reshape(J.shape[:-4] + (m, k, n, 2, 2))
    return ne.jones_c2r(J)


def manifold_average_mesh(Y_r8, axis_name, nf_total: int, m: int,
                          k: int, n: int, niter: int = 20):
    """Mesh version of calculate_manifold_average over the freq axis.

    Y_r8: [Fl, M, K, N, 8] local shard (Fl subbands per device). Each
    (m, k) block is rotated by ONE unitary toward the cross-frequency
    average; the reference block is the globally-first subband.
    ``axis_name=None`` means all subbands are local (single-device
    blocked path): psums become local sums.
    """
    psum = ((lambda x: x) if axis_name is None
            else (lambda x: jax.lax.psum(x, axis_name)))
    X0 = _blocks(Y_r8)                      # [Fl, MK, 2N, 2] complex
    # broadcast only the globally-first subband's block as the reference
    # (cheaper than all_gathering the whole array to read one element)
    if axis_name is None:
        ref = X0[0]
    else:
        is_first = (jax.lax.axis_index(axis_name) == 0)
        ref = psum(jnp.where(is_first, X0[0], jnp.zeros_like(X0[0])))

    Xp = jax.vmap(lambda Xf: mf.procrustes_project(ref, Xf))(X0)

    def body(Xp, _):
        mean = psum(jnp.sum(Xp, axis=0)) / nf_total
        Xp = jax.vmap(lambda Xf: mf.procrustes_project(mean, Xf))(Xp)
        return Xp, None

    Xp, _ = jax.lax.scan(body, Xp, None, length=niter)
    mean = psum(jnp.sum(Xp, axis=0)) / nf_total
    Xout = jax.vmap(lambda Xf: mf.procrustes_project(mean, Xf))(X0)
    return _unblocks(Xout, m, k, n)


def _emit_deferred(pend, interval):
    """Emit the host loop's collected per-iteration admm_iter records
    in ONE batched device->host fetch AFTER the loop (overlap-
    preserving: tracing never serializes the ADMM dispatch chain
    behind per-iteration float() syncs). ``pend``: (iter, r1_mean,
    dual|None, rho_mean) device scalars, copies started async.
    Feeds BOTH telemetry sinks — the diag trace (a no-op without a
    tracer) and the obs registry (consensus-residual gauges + the
    iteration counter); ``pend`` is only collected when one of the two
    is active, so the disabled path stays sync-free."""
    if not pend:
        return
    from sagecal_tpu import sched as _sched
    _sched.start_host_copy(*[x for rec in pend for x in rec[1:]
                             if x is not None])
    for it, r1m, dual, rhom in pend:
        r1 = float(np.asarray(r1m))
        du = 0.0 if dual is None else float(np.asarray(dual))
        rho = float(np.asarray(rhom))
        dtrace.emit("admm_iter", interval=interval, iter=it,
                    r1_mean=r1, dual=du, rho_mean=rho, deferred=True)
        if obs.active():
            obs.inc("admm_iterations_total")
            obs.set_gauge("admm_primal_residual", r1)
            obs.set_gauge("admm_dual_residual", du)
            obs.set_gauge("admm_rho_mean", rho)


def make_admm_runner(dsky, sta1, sta2, cidx, cmask, n_stations: int,
                     fdelta: float, B_poly: np.ndarray, cfg: ADMMConfig,
                     mesh: Mesh, nf_total: int, with_shapelets: bool = False,
                     spatial_coords=None, host_loop: bool = False,
                     dobeam: int = 0, nbase: int | None = None,
                     donate: bool = True, timer: list | None = None,
                     _return_parts: bool = False):
    """Build the jitted per-timeslot consensus-ADMM program.

    Returns ``run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F_r8)`` operating
    on [F, ...] arrays sharded over the mesh "freq" axis; gives back
    (JF_r8, Z, rhoF, res0, res1, r1_per_admm, dual_per_admm, Y0F_r8)
    where Y0F is the manifold-projected rho*J of iteration 0 (the MDL
    input, master :815-822).

    B_poly: [Fpad, P] polynomial basis (host numpy, replicated); when the
    staged subband axis Fpad exceeds the real count ``nf_total`` (uneven
    F over the mesh), rows >= nf_total must be zero and the caller
    replicates some real subband's data into the padded slots — they are
    masked out of every collective.
    spatial_coords: ([Mt] r, [Mt] theta) per-effective-cluster polar
    centroids (spatial.cluster_polar_coords) — required when
    cfg.spatialreg is set.
    host_loop: run the ADMM iteration loop on the host, one bounded
    jitted execution per iteration (identical math; required on the
    tunneled single chip whose runtime kills long executions, and
    cheaper to compile: the scan body becomes a reusable program).
    donate: host-loop only — donate the ADMM carry buffers to each body
    execution (in-place reuse; bit-identical results, gated by
    tests/test_donation.py). False keeps every input buffer alive, for
    embedders that hold references across iterations.
    timer: host-loop only — optional list receiving
    ("iter0"|"body[k]", seconds) per device execution, the same
    telemetry contract as make_admm_runner_blocked. The returned
    runner also exposes ``run.consensus_program`` — the per-iteration
    consensus half (Z psum + duals + BB rho) as its OWN mesh program,
    so the multichip harness (tools_dev/northstar.py --multichip) can
    time the collective overhead separately from the J-update solves.

    Dtype policy (MIGRATION.md "Dtype policy"): ``x8F``/``wtF`` may
    arrive in the reduced storage dtype (cli_mpi stages them per
    ``--dtype-policy``; ``cfg.sage.dtype_policy`` rides into every
    sagefit call, which owns the storage/accumulate split). The
    consensus state itself — Y, Z, BZ, rho, and the polynomial basis —
    NEVER quantizes: it derives from the f32 Jones state (``JF.dtype``
    below), so the ADMM convergence analysis is untouched by the
    policy and the Z psum collectives move f32.
    """
    from sagecal_tpu.consensus import spatial as sp
    from sagecal_tpu.rime import predict as rp

    M = int(np.asarray(cmask).shape[0])
    K = int(np.asarray(cmask).shape[1])
    N = n_stations
    Ppoly = B_poly.shape[1]
    Bfull = jnp.asarray(B_poly)            # [F, P] replicated

    # --- spatial regularization setup (master :294-397), host-side once.
    # Phi blocks live on the padded (m, k) grid: padded chunk slots get
    # zero blocks so they never contribute to Phikk or the Z update.
    spat = None
    if cfg.spatialreg is not None:
        sh_l2, sh_mu, sh_n0, fista_iters, cadence = cfg.spatialreg
        rr_c, tt_c = spatial_coords
        G = int(sh_n0) * int(sh_n0)
        Phi, Phikk = sp.phi_padded(cmask, rr_c, tt_c, sh_n0, sh_l2)
        # stage complex as re/im pairs (no complex host<->device transfer)
        spat = dict(
            Phi_ri=jnp.asarray(np.stack([Phi.real, Phi.imag], -1)),
            Phikk_ri=jnp.asarray(np.stack([Phikk.real, Phikk.imag], -1)),
            mu=float(sh_mu), iters=int(fista_iters), cadence=int(cadence),
            G=G)

    cidx_j = jnp.asarray(cidx)
    cmask_j = jnp.asarray(cmask)
    sta1_j = jnp.asarray(sta1)
    sta2_j = jnp.asarray(sta2)

    from sagecal_tpu.io import dataset as _dsmod
    # sta1 is per ROW ([nbase*tilesz]); the caller supplies the true
    # baseline count for the row->timeslot map the beam indexes with
    tslot_j = None
    if dobeam:
        if nbase is None:
            raise ValueError("dobeam needs nbase (the per-timeslot "
                             "baseline count) for the row->tslot map")
        tslot_j = jnp.asarray(
            _dsmod.row_tslot(len(np.asarray(sta1)), nbase))

    def coh_for(u, v, w, freq, beam=None):
        # with -B: per-subband beam tables folded into the source sum
        # (precalculate_coherencies_multifreq_withbeam, the slaves'
        # predict path predict_withbeam.c:690)
        return rp.coherencies(dsky, u, v, w, freq[None], fdelta,
                              with_shapelets=with_shapelets,
                              beam=beam, dobeam=dobeam, tslot=tslot_j,
                              sta1=sta1_j, sta2=sta2_j)[:, :, 0]

    # rows are [tilesz, nbase] per subband: forward the baseline period
    # to the solvers' normal-equation assembly (normal_eq row_period)
    sage_cfg = (cfg.sage if not nbase
                else cfg.sage._replace(nbase=int(nbase)))

    def local_solve_plain(x8, u, v, w, wt, J_r8, freq, beam=None):
        coh = coh_for(u, v, w, freq, beam)
        J, info = sage.sagefit(x8, coh, sta1_j, sta2_j, cidx_j, cmask_j,
                               ne.jones_r2c(J_r8), N, wt, config=sage_cfg)
        return ne.jones_c2r(J), info["res_0"], info["res_1"]

    def local_solve_admm(x8, u, v, w, wt, J_r8, freq, Y_r8, BZ_r8, rho_m,
                         beam=None):
        coh = coh_for(u, v, w, freq, beam)
        # ADMM iterations k>0 always warm-start from the previous
        # iterate, so cluster groups (inflight>1) skip the cold-start
        # width restriction; iteration 0 (local_solve_plain, sage_cfg
        # unmodified) keeps it
        scfg = sage_cfg._replace(max_lbfgs=0, inflight_warm=True)
        J, info = sage.sagefit(x8, coh, sta1_j, sta2_j, cidx_j, cmask_j,
                               ne.jones_r2c(J_r8), N, wt, config=scfg,
                               admm=(Y_r8, BZ_r8, rho_m))
        return ne.jones_c2r(J), info["res_0"], info["res_1"]

    axis = "freq"

    def _brow(Fl, ax=axis):
        # per-subband basis rows: gather local rows from the replicated
        # Bfull via the global subband index of each local row. ax=None:
        # everything is local (single-device blocked path).
        dev_idx = 0 if ax is None else jax.lax.axis_index(ax)
        local_ids = dev_idx * Fl + jnp.arange(Fl, dtype=jnp.int32)
        return Bfull[local_ids]                  # [Fl, P]

    def _fmask(Fl, dtype, ax=axis):
        """[Fl, 1] 1.0 for real subbands, 0.0 for padded slots (global
        index >= nf_total when the caller padded F up to the mesh)."""
        dev_idx = 0 if ax is None else jax.lax.axis_index(ax)
        local_ids = dev_idx * Fl + jnp.arange(Fl, dtype=jnp.int32)
        return (local_ids < nf_total).astype(dtype)[:, None]

    # rho for ALL subbands (for Bii): [M, F]
    def all_rho(rhoF, ax=axis):
        if ax is None:
            return rhoF.T
        g = jax.lax.all_gather(rhoF, ax)         # [ndev, Fl, M]
        return g.reshape(-1, M).T                # [M, F]

    def _alpha_vec(rho_m, dtype):
        if spat is None:
            return None
        # per-cluster alpha scaled by initial rho, =alpha at max rho
        # (sagecal_master.cpp:577-579; matters with a -G rho file)
        return (cfg.federated_alpha * rho_m
                / jnp.maximum(jnp.max(rho_m), 1e-30)).astype(dtype)

    def z_update(Brow, YF, rhoF, alpha_vec, Zbar=None, Xd=None, ax=axis):
        """z = sum_f B_f Y_f where YF already holds Y + rho J as sent
        to the master (slave :686-700); Z = Bii z (master :755-779).
        With spatial reg the prior pulls in: z += alpha Zbar - X and
        Bii gains the federated +alpha I (master :668-673,:768-775)."""
        zsum_local = jnp.einsum("fp,fmknr->mpknr", Brow, YF)
        zsum = (zsum_local if ax is None
                else jax.lax.psum(zsum_local, ax))
        if Zbar is not None:
            # alphak[cm] Zbar - X (master :768-775)
            zsum = zsum + alpha_vec[:, None, None, None, None] * Zbar - Xd
        Bii = cpoly.find_prod_inverse(
            Bfull, all_rho(rhoF, ax).astype(YF.dtype), alpha=alpha_vec)
        return cpoly.z_from_contributions(zsum, Bii)

    def spatial_step(Z, Zbar, Xd, dtype):
        """FISTA prox + Zbar/X refresh (master :789-814):
        Zbar <- Zspat Phi from the FISTA solve on Z; X += alpha(Z-Zbar).
        All replicated ops."""
        from sagecal_tpu.consensus import spatial as sp
        Phi = jax.lax.complex(spat["Phi_ri"][..., 0],
                              spat["Phi_ri"][..., 1])
        Phikk = jax.lax.complex(spat["Phikk_ri"][..., 0],
                                spat["Phikk_ri"][..., 1])
        cdt = jnp.complex64 if dtype == jnp.float32 else jnp.complex128
        Zb = sp.z_r8_to_blocks(Z).astype(cdt)       # [MK, 2PN, 2]
        Zspat = sp.fista_spatialreg(Zb, Phikk.astype(cdt),
                                    Phi.astype(cdt), spat["mu"],
                                    spat["iters"])
        Zbar_new = sp.blocks_to_z_r8(
            sp.spatial_predict(Zspat, Phi.astype(cdt)),
            M, Ppoly, K, N).astype(Z.dtype)
        Xd_new = Xd + cfg.federated_alpha * (Z - Zbar_new)
        return Zbar_new, Xd_new

    def iter0_post(JF, res0, res1, fratioF, ax=axis):
        """Everything after iteration 0's solves: dual seed + manifold
        average + first Z/dual update. Shared by the mesh path (ax =
        mesh axis, JF local) and the blocked path (ax=None, JF full)."""
        Fl = JF.shape[0]
        dtype = JF.dtype
        Brow = _brow(Fl, ax)
        fm = _fmask(Fl, dtype, ax)               # [Fl, 1] padded-slot mask
        fm5 = fm[:, :, None, None, None]         # [Fl, 1, 1, 1, 1]
        # per-(subband, cluster) rho scaled by unflagged fraction; cfg.rho
        # may be a scalar or an [M] per-cluster array (readsky.c:780 -G)
        rho_m = jnp.broadcast_to(jnp.asarray(cfg.rho, dtype), (M,))
        rhoF = rho_m[None, :] * fratioF[:, None] * fm * jnp.ones(
            (Fl, M), dtype)
        alpha_vec = _alpha_vec(rho_m, dtype)

        # padded slots contribute exact zeros to every collective (the
        # where also stops a non-finite padded J from poisoning 0*J)
        YF = jnp.where(fm5 > 0,
                       rhoF[..., None, None, None]
                       * JF.reshape(Fl, M, K, N, 8), 0.0)
        YF = manifold_average_mesh(YF, ax, nf_total, M, K, N,
                                   cfg.manifold_iters)
        YF = jnp.where(fm5 > 0, YF, 0.0)
        Y0F = YF     # manifold-projected rho*J: the MDL input (:815-822)

        # spatial-reg state (replicated); zeros when disabled
        Zbar = jnp.zeros((M, Ppoly, K, N, 8), dtype)
        Xd = jnp.zeros_like(Zbar)

        # iteration 0 Z update: Y currently = rho*J (manifold-aligned)
        Z = z_update(Brow, YF, rhoF, alpha_vec, ax=ax)
        if spat is not None:
            # admm==0 matches !(admm % cadence) (master :789)
            Zbar, Xd = spatial_step(Z, Zbar, Xd, dtype)
        BZ = jnp.einsum("fp,mpknr->fmknr", Brow, Z)
        YF = YF - rhoF[..., None, None, None] * BZ   # dual (slave :750)

        carry = (JF, YF, Z, rhoF, YF, JF.reshape(Fl, M, K, N, 8),
                 Zbar, Xd, rhoF)
        return carry, res0, res1, Y0F

    def _per_subband(fn):
        """vmap over the local subband axis — except at width 1, where
        the axis-free call avoids the measured 25-40% unit-vmap layout
        penalty on the latency-bound solver ops (see
        sage.sagefit_host_tiles' T=1 fast path; same physics). Width is
        a trace-time constant, so this is free."""
        def call(*args):
            lead = args[0].shape[0]
            if lead != 1:
                return jax.vmap(fn)(*args)
            sq = [None if a is None
                  else jax.tree.map(lambda x: x[0], a) for a in args]
            out = fn(*sq)
            return jax.tree.map(lambda x: x[None], out)
        return call

    def iter0_local(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                    beamF=None):
        """ADMM iteration 0 on the LOCAL shard: plain solve + post."""
        JF, res0, res1 = _per_subband(local_solve_plain)(
            x8F, uF, vF, wF, wtF, J0F, freqF, beamF)
        return iter0_post(JF, res0, res1, fratioF)

    def body_post(Jr, r0, r1, carry, it, ax=axis):
        """Everything after iteration k>0's solves (slave :686-770)."""
        JF, YF, Z, rhoF, Yhat_prev, Jprev, Zbar, Xd, rho_upper = carry
        Fl = Jr.shape[0]
        dtype = Jr.dtype
        Brow = _brow(Fl, ax)
        fm = _fmask(Fl, dtype, ax)
        fm5 = fm[:, :, None, None, None]
        rho_m = jnp.broadcast_to(jnp.asarray(cfg.rho, dtype), (M,))
        alpha_vec = _alpha_vec(rho_m, dtype)

        J5 = Jr.reshape(Fl, M, K, N, 8)
        YF = jnp.where(fm5 > 0,
                       YF + rhoF[..., None, None, None] * J5, 0.0)
        Zold = Z
        if spat is None:
            Z = z_update(Brow, YF, rhoF, alpha_vec, ax=ax)
        else:
            Z = z_update(Brow, YF, rhoF, alpha_vec, Zbar, Xd, ax=ax)
            Zbar, Xd = jax.lax.cond(
                it % spat["cadence"] == 0,
                lambda z, zb, xd: spatial_step(z, zb, xd, dtype),
                lambda z, zb, xd: (zb, xd),
                Z, Zbar, Xd)
        BZn = jnp.einsum("fp,mpknr->fmknr", Brow, Z)
        # Yhat for BB rho uses BZ_old (slave :724-732, TAG_CONSENSUS_OLD)
        Yhat = jnp.where(fm5 > 0,
                         YF - rhoF[..., None, None, None] * jnp.einsum(
                             "fp,mpknr->fmknr", Brow, Zold), 0.0)
        YF = jnp.where(fm5 > 0,
                       YF - rhoF[..., None, None, None] * BZn, 0.0)

        if cfg.adaptive_rho:
            rhoF = jax.vmap(
                lambda r, ru, dy, dj: cpoly.update_rho_bb(
                    r, ru, dy, dj, axes=(1, 2, 3))
            )(rhoF, rho_upper, Yhat - Yhat_prev, J5 - Jprev)
            rhoF = jnp.where(fm > 0, rhoF, 0.0)  # BB on padded: 0/0 guard

        dual = jnp.linalg.norm(Z - Zold) / np.sqrt(Z.size)
        return (Jr, YF, Z, rhoF, Yhat, J5, Zbar, Xd, rho_upper), \
            (r0, r1, dual)

    def body_local(x8F, uF, vF, wF, freqF, wtF, carry, it, beamF=None):
        """One ADMM iteration k>0 on the LOCAL shard (slave :686-770)."""
        Fl = x8F.shape[0]
        Brow = _brow(Fl)
        BZ = jnp.einsum("fp,mpknr->fmknr", Brow, carry[2])
        Jr, r0, r1 = _per_subband(local_solve_admm)(
            x8F, uF, vF, wF, wtF, carry[0], freqF, carry[1], BZ,
            carry[3], beamF)
        return body_post(Jr, r0, r1, carry, it)

    if _return_parts:
        # building blocks for make_admm_runner_blocked (same math,
        # different execution granularity)
        return dict(local_solve_plain=local_solve_plain,
                    local_solve_admm=local_solve_admm,
                    iter0_post=iter0_post, body_post=body_post,
                    _brow=_brow, _per_subband=_per_subband)

    def admm_program(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                     *beam_rest):
        # shapes here are the LOCAL shard: [Fl, ...]
        beamF = beam_rest[0] if beam_rest else None
        carry, res0, res1, Y0F = iter0_local(x8F, uF, vF, wF, freqF, wtF,
                                             fratioF, J0F, beamF)

        def body(carry, it):
            return body_local(x8F, uF, vF, wF, freqF, wtF, carry, it,
                              beamF)

        carry, (r0s, r1s, duals) = jax.lax.scan(
            body, carry, jnp.arange(1, max(cfg.n_admm, 1),
                                    dtype=jnp.int32))
        JF, YF, Z, rhoF = carry[0], carry[1], carry[2], carry[3]
        return JF, Z, rhoF, res0, res1, r1s, duals, Y0F

    from sagecal_tpu.compat import shard_map
    spec_f = P(axis)
    spec_r = P()
    nin = 8 + (1 if dobeam else 0)     # beam pytree rides a prefix spec
    if not host_loop:
        prog = shard_map(
            admm_program, mesh=mesh,
            in_specs=(spec_f,) * nin,
            out_specs=(spec_f, spec_r, spec_f, spec_f, spec_f,
                       P(None, axis), spec_r, spec_f),
            check_vma=False)
        return jax.jit(prog)

    # --- host-driven ADMM loop: one bounded device execution per ADMM
    # iteration (the tunneled single-chip runtime kills executions over
    # ~60 s; a fully traced n_admm-iteration program over folded subbands
    # exceeds it — and this is also the natural structure for streaming
    # telemetry per iteration, like the master's per-iter prints).
    carry_specs = (spec_f, spec_f, spec_r, spec_f, spec_f, spec_f,
                   spec_r, spec_r, spec_f)

    def iter0_flat(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                   *beam_rest):
        carry, res0, res1, Y0F = iter0_local(
            x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
            beam_rest[0] if beam_rest else None)
        return carry + (res0, res1, Y0F)

    def body_flat(x8F, uF, vF, wF, freqF, wtF, JF, YF, Z, rhoF, Yhat,
                  Jprev, Zbar, Xd, rho_upper, it, *beam_rest):
        carry = (JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd, rho_upper)
        carry, (r0, r1, dual) = body_local(
            x8F, uF, vF, wF, freqF, wtF, carry, it,
            beam_rest[0] if beam_rest else None)
        return carry + (r0, r1, dual)

    beam_specs = (spec_f,) if dobeam else ()
    prog0 = jax.jit(shard_map(
        iter0_flat, mesh=mesh, in_specs=(spec_f,) * 8 + beam_specs,
        out_specs=carry_specs + (spec_f, spec_f, spec_f),
        check_vma=False))
    # the ADMM carry (J/Y/Z/rho accumulators + BB state) is DONATED to
    # each body execution: every iteration rebinds the carry from the
    # program's outputs, so XLA reuses the buffers in place instead of
    # allocating a fresh accumulator set per ADMM iteration
    progb = jax.jit(shard_map(
        body_flat, mesh=mesh,
        in_specs=(spec_f,) * 6 + carry_specs + (spec_r,) + beam_specs,
        out_specs=carry_specs + (spec_f, spec_f, spec_r),
        check_vma=False),
        donate_argnums=tuple(range(6, 15)) if donate else ())

    # consensus-only program: everything one ADMM body iteration does
    # AFTER the J-update solves (z-sum psum, Bii solve, duals, BB rho),
    # as its own mesh execution — the measured collective-overhead
    # probe. Never donated: the caller times it repeatedly on one carry.
    def cons_flat(Jr, r0, r1, JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd,
                  rho_upper, it):
        carry = (JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd, rho_upper)
        carry, (r0o, r1o, dual) = body_post(Jr, r0, r1, carry, it)
        return carry + (r0o, r1o, dual)

    prog_cons = jax.jit(shard_map(
        cons_flat, mesh=mesh,
        in_specs=(spec_f, spec_f, spec_f) + carry_specs + (spec_r,),
        out_specs=carry_specs + (spec_f, spec_f, spec_r),
        check_vma=False))

    n_runs = [0]    # runner invocation ordinal = interval, for traces

    import time as _time

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    def run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F, *beam_rest):
        interval = n_runs[0]
        n_runs[0] += 1
        t0 = _time.perf_counter()
        out = prog0(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                    *beam_rest)
        _t("iter0", t0, out[0])
        carry, (res0, res1, Y0F) = out[:9], out[9:]
        # per-iteration convergence records are DEFERRED: the means are
        # dispatched on device here (gated, cheap) and fetched in ONE
        # batched transfer after the loop, so tracing never inserts a
        # per-iteration host sync into the ADMM chain
        pend = []
        if dtrace.active() or obs.active():
            pend.append((0, jnp.mean(res1), None, jnp.mean(carry[3])))
        r1s, duals = [], []
        for it in range(1, max(cfg.n_admm, 1)):
            t0 = _time.perf_counter()
            out = progb(x8F, uF, vF, wF, freqF, wtF, *carry,
                        jnp.asarray(it, jnp.int32), *beam_rest)
            _t(f"body[{it}]", t0, out[0])
            carry, (_, r1, dual) = out[:9], out[9:]
            r1s.append(r1)
            duals.append(dual)
            if dtrace.active() or obs.active():
                pend.append((it, jnp.mean(r1), dual,
                             jnp.mean(carry[3])))
        _emit_deferred(pend, interval)
        JF, Z, rhoF = carry[0], carry[2], carry[3]
        F = x8F.shape[0]
        r1s_a = (jnp.stack(r1s) if r1s
                 else jnp.zeros((0, F), x8F.dtype))
        duals_a = (jnp.stack(duals) if duals
                   else jnp.zeros((0,), x8F.dtype))
        return JF, Z, rhoF, res0, res1, r1s_a, duals_a, Y0F

    run.consensus_program = prog_cons
    return run


def make_admm_runner_blocked(dsky, sta1, sta2, cidx, cmask,
                             n_stations: int, fdelta: float,
                             B_poly: np.ndarray, cfg: ADMMConfig,
                             nf_total: int, block_f: int,
                             with_shapelets: bool = False,
                             dobeam: int = 0, nbase: int | None = None,
                             device=None, timer=None):
    """Single-device consensus ADMM with the J-update split into subband
    BLOCKS of ``block_f`` — one bounded device execution per block, tiny
    consensus executions in between. Identical math to
    :func:`make_admm_runner` (it reuses the same iter0_post/body_post
    consensus code with ax=None), built for shapes where one folded
    J-update over all subbands would exceed the tunneled chip's
    per-execution wall-clock kill (~60 s): the north-star 64-station x
    100-direction x 32-subband problem.

    Spatial regularization is not offered here (use the mesh runner).
    ``timer``: optional list that receives (label, seconds) tuples for
    per-execution telemetry.
    """
    import time as _time

    if cfg.spatialreg is not None:
        raise ValueError("blocked runner does not support -X spatial "
                         "regularization; use make_admm_runner")
    # borrow the full closure set from make_admm_runner on a 1-device
    # mesh; we only use its ax=None entry points, never its shard_map
    # programs
    devs = [device] if device is not None else jax.devices()[:1]
    mesh = Mesh(np.array(devs), ("freq",))
    parts = make_admm_runner(
        dsky, sta1, sta2, cidx, cmask, n_stations, fdelta, B_poly, cfg,
        mesh, nf_total, with_shapelets=with_shapelets,
        dobeam=dobeam, nbase=nbase,
        _return_parts=True)
    local_solve_plain = parts["local_solve_plain"]
    local_solve_admm = parts["local_solve_admm"]
    iter0_post = parts["iter0_post"]
    body_post = parts["body_post"]
    _brow = parts["_brow"]

    # the shared unit-width wrapper: block_f == 1 (the north-star's
    # best plan) takes the axis-free call, avoiding the unit-vmap
    # layout penalty
    _per_subband = parts["_per_subband"]
    solve0 = jax.jit(_per_subband(local_solve_plain))
    solveb = jax.jit(_per_subband(local_solve_admm))
    # donate the block-solved Jones and the ADMM carry into the
    # consensus steps (same in-place reuse as make_admm_runner's
    # host-loop donation; callers rebind both from the outputs)
    cons0 = jax.jit(lambda JF, res0, res1, fratioF: iter0_post(
        JF, res0, res1, fratioF, ax=None), donate_argnums=(0,))
    consb = jax.jit(lambda Jr, r0, r1, carry, it: body_post(
        Jr, r0, r1, carry, it, ax=None), donate_argnums=(0, 3))
    bz_prog = jax.jit(
        lambda Z, Brow: jnp.einsum("fp,mpknr->fmknr", Brow, Z))

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    n_runs = [0]    # runner invocation ordinal = interval, for traces

    def run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F, *beam_rest):
        interval = n_runs[0]
        n_runs[0] += 1
        beamF = beam_rest[0] if beam_rest else None
        F = x8F.shape[0]
        Brow_full = _brow(F, None)          # eager: Bfull[:F]
        blocks = [slice(b, min(b + block_f, F))
                  for b in range(0, F, block_f)]

        def take(a, sl):
            """Block slice, padded to block_f by repeating the first
            row so every block compiles to ONE program shape (a ragged
            tail block would otherwise double the solve compiles)."""
            ab = a[sl]
            short = block_f - ab.shape[0]
            if short:
                ab = jnp.concatenate(
                    [ab, jnp.broadcast_to(ab[:1],
                                          (short,) + ab.shape[1:])])
            return ab

        # constant per-tile inputs: slice/pad each block ONCE, not per
        # ADMM iteration
        const_blocks = [tuple(take(a, sl)
                              for a in (x8F, uF, vF, wF, wtF, freqF))
                        for sl in blocks]
        beam_blocks = None
        if beamF is not None:
            beam_blocks = [jax.tree.map(lambda a: take(a, sl), beamF)
                           for sl in blocks]

        def blockwise(fn, *per_iter):
            """fn(x8, u, v, w, wt, freq, *per-iteration block args)."""
            Js, r0s, r1s = [], [], []
            for i, sl in enumerate(blocks):
                t0 = _time.perf_counter()
                bb = (beam_blocks[i],) if beam_blocks is not None else ()
                Jb, r0b, r1b = fn(*const_blocks[i],
                                  *[take(a, sl) for a in per_iter], *bb)
                _t(f"solve[{i}]", t0, Jb)
                nreal = sl.stop - sl.start
                Js.append(Jb[:nreal])
                r0s.append(r0b[:nreal])
                r1s.append(r1b[:nreal])
            return (jnp.concatenate(Js), jnp.concatenate(r0s),
                    jnp.concatenate(r1s))

        def solve0_re(x8, u, v, w, wt, freq, J0, *bb):
            return solve0(x8, u, v, w, wt, J0, freq, *bb)

        def solveb_re(x8, u, v, w, wt, freq, J, Y, BZ, rho, *bb):
            return solveb(x8, u, v, w, wt, J, freq, Y, BZ, rho, *bb)

        JF, res0, res1 = blockwise(solve0_re, J0F)
        t0 = _time.perf_counter()
        carry, res0, res1, Y0F = cons0(JF, res0, res1, fratioF)
        _t("cons0", t0, carry[2])
        r1h, dualh = [], []
        pend = []       # deferred admm_iter records (no per-iter sync)
        for it in range(1, max(cfg.n_admm, 1)):
            BZ = bz_prog(carry[2], Brow_full)
            Jr, r0, r1 = blockwise(solveb_re, carry[0], carry[1], BZ,
                                   carry[3])
            t0 = _time.perf_counter()
            carry, (r0, r1, dual) = consb(Jr, r0, r1, carry,
                                          jnp.asarray(it, jnp.int32))
            _t(f"cons[{it}]", t0, carry[2])
            r1h.append(r1)
            dualh.append(dual)
            if dtrace.active() or obs.active():
                pend.append((it, jnp.mean(r1), dual,
                             jnp.mean(carry[3])))
        _emit_deferred(pend, interval)
        JF, Z, rhoF = carry[0], carry[2], carry[3]
        r1s_a = (jnp.stack(r1h) if r1h
                 else jnp.zeros((0, F), x8F.dtype))
        duals_a = (jnp.stack(dualh) if dualh
                   else jnp.zeros((0,), x8F.dtype))
        return JF, Z, rhoF, res0, res1, r1s_a, duals_a, Y0F

    return run
