"""Distributed consensus-ADMM across frequency subbands on a device mesh.

Capability parity with the reference's MPI master/slave per-timeslot loop
(``src/MPI/sagecal_master.cpp:621-890`` + ``sagecal_slave.cpp:488-930``,
SURVEY.md section 3.3), re-architected as ONE SPMD program over a
``jax.sharding.Mesh`` with a "freq" axis (SURVEY.md P9/P10/C1):

- the hub-and-spoke MPI tag protocol disappears: J/Y updates run
  shard-local per subband; the master's gather(Y) + Z-solve + broadcast(BZ)
  becomes ``psum`` over the subband axis + a replicated small solve;
- ADMM iteration 0: plain SAGE solve, dual seed Y = rho*J, then manifold
  averaging of Y across frequency (master :739-751) — here a psum-based
  Procrustes averaging (consensus/manifold.py);
- iterations k>0: augmented-Lagrangian SAGE solve (admm_solve.c:221
  semantics via solvers.sage with the admm term), Y += rho*J, z-sum via
  psum, Z = Bii z, Y -= rho*BZ (slave :686-770);
- optional Barzilai-Borwein adaptive rho per (subband, cluster)
  (slave :782-786, consensus_poly.c:923);
- rho is scaled by each subband's unflagged-data fraction
  (master :646-650).

Data multiplexing (Scurrent rotation, master :883-889) is unnecessary when
every subband owns a shard; when F exceeds the mesh size, multiple subbands
ride one shard via the local leading axis — same effect, no rotation.

When F does not divide the mesh size, the caller pads the subband axis up
to ``Fl * ndev`` (replicating a real subband's data so padded solves stay
numerically tame) and passes the REAL count as ``nf_total``: rows with
global index >= nf_total get zero basis rows in the padded ``B_poly``,
zero rho, and are masked out of the manifold mean and every dual/Y
quantity — so 7 subbands use 8 devices instead of shrinking the mesh to a
divisor (the reference's analogue is idle slaves, sagecal_master.cpp:155).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sagecal_tpu.consensus import manifold as mf
from sagecal_tpu.consensus import poly as cpoly
from sagecal_tpu.diag import trace as dtrace
from sagecal_tpu.obs import metrics as obs
from sagecal_tpu.solvers import normal_eq as ne
from sagecal_tpu.solvers import sage


class ADMMConfig(NamedTuple):
    n_admm: int = 10
    npoly: int = 2
    poly_type: int = 2
    # scalar, or [M] per-cluster array: an explicit -G rho file, or a
    # banked schedule seeded by --prior-cache read (serve/priors.py —
    # the previous run's converged per-cluster rho; -G wins over it)
    rho: float = 5.0
    adaptive_rho: bool = False
    manifold_iters: int = 20     # master :740 Niter
    sage: sage.SageConfig = sage.SageConfig()
    # -X l2,l1,order,fista_iters,cadence (README.md:160-166); None = off
    spatialreg: tuple | None = None
    federated_alpha: float = 0.0  # -u : alpha of the spatial/federated prior


def pad_subbands(arrays, B_poly, nf: int, ndev: int):
    """THE padding contract for uneven F over the mesh, in one place.

    arrays: sequence of host arrays with a leading real-subband axis
    [nf, ...]. Returns (padded_arrays, padded_B, fpad): each array's
    leading axis padded to ``fpad = ceil(nf/ndev)*ndev`` (ndev may exceed
    nf: fpad then equals ndev) by replicating the first subband — padded
    solves stay numerically tame — and B_poly gains zero rows so padded
    slots contribute nothing to any collective. Pass the REAL count nf as
    ``nf_total`` to :func:`make_admm_runner`; slice every per-subband
    output back to [:nf] on the host.
    """
    ndev = max(int(ndev), 1)
    fpad = -(-max(nf, ndev) // ndev) * ndev
    if fpad == nf:
        return list(arrays), np.asarray(B_poly), fpad
    out = []
    for a in arrays:
        a = np.asarray(a)
        out.append(np.concatenate(
            [a, np.broadcast_to(a[:1], (fpad - nf,) + a.shape[1:])]))
    B = np.asarray(B_poly)
    B = np.vstack([B, np.zeros((fpad - nf, B.shape[1]), B.dtype)])
    return out, B, fpad


def _blocks(J_r8):
    """[.., M, K, N, 8] real Jones -> [.., M*K, 2N, 2] complex blocks."""
    J = ne.jones_r2c(J_r8)
    shp = J.shape
    J = J.reshape(shp[:-5] + (shp[-5] * shp[-4], shp[-3], 2, 2))
    return mf.jones_to_blocks(J)


def _unblocks(X, m, k, n):
    J = mf.blocks_to_jones(X)
    J = J.reshape(J.shape[:-4] + (m, k, n, 2, 2))
    return ne.jones_c2r(J)


def manifold_average_mesh(Y_r8, axis_name, nf_total: int, m: int,
                          k: int, n: int, niter: int = 20):
    """Mesh version of calculate_manifold_average over the freq axis.

    Y_r8: [Fl, M, K, N, 8] local shard (Fl subbands per device). Each
    (m, k) block is rotated by ONE unitary toward the cross-frequency
    average; the reference block is the globally-first subband.
    ``axis_name=None`` means all subbands are local (single-device
    blocked path): psums become local sums.
    """
    psum = ((lambda x: x) if axis_name is None
            else (lambda x: jax.lax.psum(x, axis_name)))
    X0 = _blocks(Y_r8)                      # [Fl, MK, 2N, 2] complex
    # broadcast only the globally-first subband's block as the reference
    # (cheaper than all_gathering the whole array to read one element)
    if axis_name is None:
        ref = X0[0]
    else:
        is_first = (jax.lax.axis_index(axis_name) == 0)
        ref = psum(jnp.where(is_first, X0[0], jnp.zeros_like(X0[0])))

    Xp = jax.vmap(lambda Xf: mf.procrustes_project(ref, Xf))(X0)

    def body(Xp, _):
        mean = psum(jnp.sum(Xp, axis=0)) / nf_total
        Xp = jax.vmap(lambda Xf: mf.procrustes_project(mean, Xf))(Xp)
        return Xp, None

    Xp, _ = jax.lax.scan(body, Xp, None, length=niter)
    mean = psum(jnp.sum(Xp, axis=0)) / nf_total
    Xout = jax.vmap(lambda Xf: mf.procrustes_project(mean, Xf))(X0)
    return _unblocks(Xout, m, k, n)


def _emit_deferred(pend, interval):
    """Emit the host loop's collected per-iteration admm_iter records
    in ONE batched device->host fetch AFTER the loop (overlap-
    preserving: tracing never serializes the ADMM dispatch chain
    behind per-iteration float() syncs). ``pend``: (iter, r1_mean,
    dual|None, rho_mean) device scalars, copies started async.
    Feeds BOTH telemetry sinks — the diag trace (a no-op without a
    tracer) and the obs registry (consensus-residual gauges + the
    iteration counter); ``pend`` is only collected when one of the two
    is active, so the disabled path stays sync-free."""
    if not pend:
        return
    from sagecal_tpu import sched as _sched
    _sched.start_host_copy(*[x for rec in pend for x in rec[1:]
                             if x is not None])
    for it, r1m, dual, rhom in pend:
        r1 = float(np.asarray(r1m))
        du = 0.0 if dual is None else float(np.asarray(dual))
        rho = float(np.asarray(rhom))
        dtrace.emit("admm_iter", interval=interval, iter=it,
                    r1_mean=r1, dual=du, rho_mean=rho, deferred=True)
        if obs.active():
            obs.inc("admm_iterations_total")
            obs.set_gauge("admm_primal_residual", r1)
            obs.set_gauge("admm_dual_residual", du)
            obs.set_gauge("admm_rho_mean", rho)


def make_admm_runner(dsky, sta1, sta2, cidx, cmask, n_stations: int,
                     fdelta: float, B_poly: np.ndarray, cfg: ADMMConfig,
                     mesh: Mesh, nf_total: int, with_shapelets: bool = False,
                     spatial_coords=None, host_loop: bool = False,
                     dobeam: int = 0, nbase: int | None = None,
                     donate: bool = True, timer: list | None = None,
                     _return_parts: bool = False):
    """Build the jitted per-timeslot consensus-ADMM program.

    Returns ``run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F_r8)`` operating
    on [F, ...] arrays sharded over the mesh "freq" axis; gives back
    (JF_r8, Z, rhoF, res0, res1, r1_per_admm, dual_per_admm, Y0F_r8)
    where Y0F is the manifold-projected rho*J of iteration 0 (the MDL
    input, master :815-822).

    B_poly: [Fpad, P] polynomial basis (host numpy, replicated); when the
    staged subband axis Fpad exceeds the real count ``nf_total`` (uneven
    F over the mesh), rows >= nf_total must be zero and the caller
    replicates some real subband's data into the padded slots — they are
    masked out of every collective.
    spatial_coords: ([Mt] r, [Mt] theta) per-effective-cluster polar
    centroids (spatial.cluster_polar_coords) — required when
    cfg.spatialreg is set.
    host_loop: run the ADMM iteration loop on the host, one bounded
    jitted execution per iteration (identical math; required on the
    tunneled single chip whose runtime kills long executions, and
    cheaper to compile: the scan body becomes a reusable program).
    donate: host-loop only — donate the ADMM carry buffers to each body
    execution (in-place reuse; bit-identical results, gated by
    tests/test_donation.py). False keeps every input buffer alive, for
    embedders that hold references across iterations.
    timer: host-loop only — optional list receiving
    ("iter0"|"body[k]", seconds) per device execution, the same
    telemetry contract as make_admm_runner_blocked. The returned
    runner also exposes ``run.consensus_program`` — the per-iteration
    consensus half (Z psum + duals + BB rho) as its OWN mesh program,
    so the multichip harness (tools_dev/northstar.py --multichip) can
    time the collective overhead separately from the J-update solves.

    Dtype policy (MIGRATION.md "Dtype policy"): ``x8F``/``wtF`` may
    arrive in the reduced storage dtype (cli_mpi stages them per
    ``--dtype-policy``; ``cfg.sage.dtype_policy`` rides into every
    sagefit call, which owns the storage/accumulate split). The
    consensus state itself — Y, Z, BZ, rho, and the polynomial basis —
    NEVER quantizes: it derives from the f32 Jones state (``JF.dtype``
    below), so the ADMM convergence analysis is untouched by the
    policy and the Z psum collectives move f32.
    """
    from sagecal_tpu.consensus import spatial as sp
    from sagecal_tpu.rime import predict as rp

    M = int(np.asarray(cmask).shape[0])
    K = int(np.asarray(cmask).shape[1])
    N = n_stations
    Ppoly = B_poly.shape[1]
    Bfull = jnp.asarray(B_poly)            # [F, P] replicated

    # --- spatial regularization setup (master :294-397), host-side once.
    # Phi blocks live on the padded (m, k) grid: padded chunk slots get
    # zero blocks so they never contribute to Phikk or the Z update.
    spat = None
    if cfg.spatialreg is not None:
        sh_l2, sh_mu, sh_n0, fista_iters, cadence = cfg.spatialreg
        rr_c, tt_c = spatial_coords
        G = int(sh_n0) * int(sh_n0)
        Phi, Phikk = sp.phi_padded(cmask, rr_c, tt_c, sh_n0, sh_l2)
        # stage complex as re/im pairs (no complex host<->device transfer)
        spat = dict(
            Phi_ri=jnp.asarray(np.stack([Phi.real, Phi.imag], -1)),
            Phikk_ri=jnp.asarray(np.stack([Phikk.real, Phikk.imag], -1)),
            mu=float(sh_mu), iters=int(fista_iters), cadence=int(cadence),
            G=G)

    cidx_j = jnp.asarray(cidx)
    cmask_j = jnp.asarray(cmask)
    sta1_j = jnp.asarray(sta1)
    sta2_j = jnp.asarray(sta2)

    from sagecal_tpu.io import dataset as _dsmod
    # sta1 is per ROW ([nbase*tilesz]); the caller supplies the true
    # baseline count for the row->timeslot map the beam indexes with
    tslot_j = None
    if dobeam:
        if nbase is None:
            raise ValueError("dobeam needs nbase (the per-timeslot "
                             "baseline count) for the row->tslot map")
        tslot_j = jnp.asarray(
            _dsmod.row_tslot(len(np.asarray(sta1)), nbase))

    def coh_for(u, v, w, freq, beam=None):
        # with -B: per-subband beam tables folded into the source sum
        # (precalculate_coherencies_multifreq_withbeam, the slaves'
        # predict path predict_withbeam.c:690)
        return rp.coherencies(dsky, u, v, w, freq[None], fdelta,
                              with_shapelets=with_shapelets,
                              beam=beam, dobeam=dobeam, tslot=tslot_j,
                              sta1=sta1_j, sta2=sta2_j)[:, :, 0]

    # rows are [tilesz, nbase] per subband: forward the baseline period
    # to the solvers' normal-equation assembly (normal_eq row_period)
    sage_cfg = (cfg.sage if not nbase
                else cfg.sage._replace(nbase=int(nbase)))

    def local_solve_plain(x8, u, v, w, wt, J_r8, freq, beam=None):
        coh = coh_for(u, v, w, freq, beam)
        J, info = sage.sagefit(x8, coh, sta1_j, sta2_j, cidx_j, cmask_j,
                               ne.jones_r2c(J_r8), N, wt, config=sage_cfg)
        return ne.jones_c2r(J), info["res_0"], info["res_1"]

    def local_solve_admm(x8, u, v, w, wt, J_r8, freq, Y_r8, BZ_r8, rho_m,
                         beam=None):
        coh = coh_for(u, v, w, freq, beam)
        # ADMM iterations k>0 always warm-start from the previous
        # iterate, so cluster groups (inflight>1) skip the cold-start
        # width restriction; iteration 0 (local_solve_plain, sage_cfg
        # unmodified) keeps it
        scfg = sage_cfg._replace(max_lbfgs=0, inflight_warm=True)
        J, info = sage.sagefit(x8, coh, sta1_j, sta2_j, cidx_j, cmask_j,
                               ne.jones_r2c(J_r8), N, wt, config=scfg,
                               admm=(Y_r8, BZ_r8, rho_m))
        return ne.jones_c2r(J), info["res_0"], info["res_1"]

    axis = "freq"

    def _brow(Fl, ax=axis):
        # per-subband basis rows: gather local rows from the replicated
        # Bfull via the global subband index of each local row. ax=None:
        # everything is local (single-device blocked path).
        dev_idx = 0 if ax is None else jax.lax.axis_index(ax)
        local_ids = dev_idx * Fl + jnp.arange(Fl, dtype=jnp.int32)
        return Bfull[local_ids]                  # [Fl, P]

    def _fmask(Fl, dtype, ax=axis):
        """[Fl, 1] 1.0 for real subbands, 0.0 for padded slots (global
        index >= nf_total when the caller padded F up to the mesh)."""
        dev_idx = 0 if ax is None else jax.lax.axis_index(ax)
        local_ids = dev_idx * Fl + jnp.arange(Fl, dtype=jnp.int32)
        return (local_ids < nf_total).astype(dtype)[:, None]

    # rho for ALL subbands (for Bii): [M, F]
    def all_rho(rhoF, ax=axis):
        if ax is None:
            return rhoF.T
        g = jax.lax.all_gather(rhoF, ax)         # [ndev, Fl, M]
        return g.reshape(-1, M).T                # [M, F]

    def _alpha_vec(rho_m, dtype):
        if spat is None:
            return None
        # per-cluster alpha scaled by initial rho, =alpha at max rho
        # (sagecal_master.cpp:577-579; matters with a -G rho file)
        return (cfg.federated_alpha * rho_m
                / jnp.maximum(jnp.max(rho_m), 1e-30)).astype(dtype)

    def z_update(Brow, YF, rhoF, alpha_vec, Zbar=None, Xd=None, ax=axis):
        """z = sum_f B_f Y_f where YF already holds Y + rho J as sent
        to the master (slave :686-700); Z = Bii z (master :755-779).
        With spatial reg the prior pulls in: z += alpha Zbar - X and
        Bii gains the federated +alpha I (master :668-673,:768-775)."""
        zsum_local = jnp.einsum("fp,fmknr->mpknr", Brow, YF)
        zsum = (zsum_local if ax is None
                else jax.lax.psum(zsum_local, ax))
        if Zbar is not None:
            # alphak[cm] Zbar - X (master :768-775)
            zsum = zsum + alpha_vec[:, None, None, None, None] * Zbar - Xd
        Bii = cpoly.find_prod_inverse(
            Bfull, all_rho(rhoF, ax).astype(YF.dtype), alpha=alpha_vec)
        return cpoly.z_from_contributions(zsum, Bii)

    def spatial_step(Z, Zbar, Xd, dtype):
        """FISTA prox + Zbar/X refresh (master :789-814):
        Zbar <- Zspat Phi from the FISTA solve on Z; X += alpha(Z-Zbar).
        All replicated ops."""
        from sagecal_tpu.consensus import spatial as sp
        Phi = jax.lax.complex(spat["Phi_ri"][..., 0],
                              spat["Phi_ri"][..., 1])
        Phikk = jax.lax.complex(spat["Phikk_ri"][..., 0],
                                spat["Phikk_ri"][..., 1])
        cdt = jnp.complex64 if dtype == jnp.float32 else jnp.complex128
        Zb = sp.z_r8_to_blocks(Z).astype(cdt)       # [MK, 2PN, 2]
        Zspat = sp.fista_spatialreg(Zb, Phikk.astype(cdt),
                                    Phi.astype(cdt), spat["mu"],
                                    spat["iters"])
        Zbar_new = sp.blocks_to_z_r8(
            sp.spatial_predict(Zspat, Phi.astype(cdt)),
            M, Ppoly, K, N).astype(Z.dtype)
        Xd_new = Xd + cfg.federated_alpha * (Z - Zbar_new)
        return Zbar_new, Xd_new

    def iter0_post(JF, res0, res1, fratioF, ax=axis):
        """Everything after iteration 0's solves: dual seed + manifold
        average + first Z/dual update. Shared by the mesh path (ax =
        mesh axis, JF local) and the blocked path (ax=None, JF full)."""
        Fl = JF.shape[0]
        dtype = JF.dtype
        Brow = _brow(Fl, ax)
        fm = _fmask(Fl, dtype, ax)               # [Fl, 1] padded-slot mask
        fm5 = fm[:, :, None, None, None]         # [Fl, 1, 1, 1, 1]
        # per-(subband, cluster) rho scaled by unflagged fraction; cfg.rho
        # may be a scalar or an [M] per-cluster array (readsky.c:780 -G)
        rho_m = jnp.broadcast_to(jnp.asarray(cfg.rho, dtype), (M,))
        rhoF = rho_m[None, :] * fratioF[:, None] * fm * jnp.ones(
            (Fl, M), dtype)
        alpha_vec = _alpha_vec(rho_m, dtype)

        # padded slots contribute exact zeros to every collective (the
        # where also stops a non-finite padded J from poisoning 0*J)
        YF = jnp.where(fm5 > 0,
                       rhoF[..., None, None, None]
                       * JF.reshape(Fl, M, K, N, 8), 0.0)
        YF = manifold_average_mesh(YF, ax, nf_total, M, K, N,
                                   cfg.manifold_iters)
        YF = jnp.where(fm5 > 0, YF, 0.0)
        Y0F = YF     # manifold-projected rho*J: the MDL input (:815-822)

        # spatial-reg state (replicated); zeros when disabled
        Zbar = jnp.zeros((M, Ppoly, K, N, 8), dtype)
        Xd = jnp.zeros_like(Zbar)

        # iteration 0 Z update: Y currently = rho*J (manifold-aligned)
        Z = z_update(Brow, YF, rhoF, alpha_vec, ax=ax)
        if spat is not None:
            # admm==0 matches !(admm % cadence) (master :789)
            Zbar, Xd = spatial_step(Z, Zbar, Xd, dtype)
        BZ = jnp.einsum("fp,mpknr->fmknr", Brow, Z)
        YF = YF - rhoF[..., None, None, None] * BZ   # dual (slave :750)

        carry = (JF, YF, Z, rhoF, YF, JF.reshape(Fl, M, K, N, 8),
                 Zbar, Xd, rhoF)
        return carry, res0, res1, Y0F

    def _per_subband(fn):
        """vmap over the local subband axis — except at width 1, where
        the axis-free call avoids the measured 25-40% unit-vmap layout
        penalty on the latency-bound solver ops (see
        sage.sagefit_host_tiles' T=1 fast path; same physics). Width is
        a trace-time constant, so this is free."""
        def call(*args):
            lead = args[0].shape[0]
            if lead != 1:
                return jax.vmap(fn)(*args)
            sq = [None if a is None
                  else jax.tree.map(lambda x: x[0], a) for a in args]
            out = fn(*sq)
            return jax.tree.map(lambda x: x[None], out)
        return call

    def iter0_local(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                    beamF=None):
        """ADMM iteration 0 on the LOCAL shard: plain solve + post."""
        JF, res0, res1 = _per_subband(local_solve_plain)(
            x8F, uF, vF, wF, wtF, J0F, freqF, beamF)
        return iter0_post(JF, res0, res1, fratioF)

    def body_post(Jr, r0, r1, carry, it, ax=axis):
        """Everything after iteration k>0's solves (slave :686-770)."""
        JF, YF, Z, rhoF, Yhat_prev, Jprev, Zbar, Xd, rho_upper = carry
        Fl = Jr.shape[0]
        dtype = Jr.dtype
        Brow = _brow(Fl, ax)
        fm = _fmask(Fl, dtype, ax)
        fm5 = fm[:, :, None, None, None]
        rho_m = jnp.broadcast_to(jnp.asarray(cfg.rho, dtype), (M,))
        alpha_vec = _alpha_vec(rho_m, dtype)

        J5 = Jr.reshape(Fl, M, K, N, 8)
        YF = jnp.where(fm5 > 0,
                       YF + rhoF[..., None, None, None] * J5, 0.0)
        Zold = Z
        if spat is None:
            Z = z_update(Brow, YF, rhoF, alpha_vec, ax=ax)
        else:
            Z = z_update(Brow, YF, rhoF, alpha_vec, Zbar, Xd, ax=ax)
            Zbar, Xd = jax.lax.cond(
                it % spat["cadence"] == 0,
                lambda z, zb, xd: spatial_step(z, zb, xd, dtype),
                lambda z, zb, xd: (zb, xd),
                Z, Zbar, Xd)
        BZn = jnp.einsum("fp,mpknr->fmknr", Brow, Z)
        # Yhat for BB rho uses BZ_old (slave :724-732, TAG_CONSENSUS_OLD)
        Yhat = jnp.where(fm5 > 0,
                         YF - rhoF[..., None, None, None] * jnp.einsum(
                             "fp,mpknr->fmknr", Brow, Zold), 0.0)
        YF = jnp.where(fm5 > 0,
                       YF - rhoF[..., None, None, None] * BZn, 0.0)

        if cfg.adaptive_rho:
            rhoF = jax.vmap(
                lambda r, ru, dy, dj: cpoly.update_rho_bb(
                    r, ru, dy, dj, axes=(1, 2, 3))
            )(rhoF, rho_upper, Yhat - Yhat_prev, J5 - Jprev)
            rhoF = jnp.where(fm > 0, rhoF, 0.0)  # BB on padded: 0/0 guard

        dual = jnp.linalg.norm(Z - Zold) / np.sqrt(Z.size)
        return (Jr, YF, Z, rhoF, Yhat, J5, Zbar, Xd, rho_upper), \
            (r0, r1, dual)

    def body_local(x8F, uF, vF, wF, freqF, wtF, carry, it, beamF=None):
        """One ADMM iteration k>0 on the LOCAL shard (slave :686-770)."""
        Fl = x8F.shape[0]
        Brow = _brow(Fl)
        BZ = jnp.einsum("fp,mpknr->fmknr", Brow, carry[2])
        Jr, r0, r1 = _per_subband(local_solve_admm)(
            x8F, uF, vF, wF, wtF, carry[0], freqF, carry[1], BZ,
            carry[3], beamF)
        return body_post(Jr, r0, r1, carry, it)

    if _return_parts:
        # building blocks for make_admm_runner_blocked (same math,
        # different execution granularity)
        return dict(local_solve_plain=local_solve_plain,
                    local_solve_admm=local_solve_admm,
                    iter0_post=iter0_post, body_post=body_post,
                    _brow=_brow, _per_subband=_per_subband,
                    Bfull=Bfull)

    def admm_program(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                     *beam_rest):
        # shapes here are the LOCAL shard: [Fl, ...]
        beamF = beam_rest[0] if beam_rest else None
        carry, res0, res1, Y0F = iter0_local(x8F, uF, vF, wF, freqF, wtF,
                                             fratioF, J0F, beamF)

        def body(carry, it):
            return body_local(x8F, uF, vF, wF, freqF, wtF, carry, it,
                              beamF)

        carry, (r0s, r1s, duals) = jax.lax.scan(
            body, carry, jnp.arange(1, max(cfg.n_admm, 1),
                                    dtype=jnp.int32))
        JF, YF, Z, rhoF = carry[0], carry[1], carry[2], carry[3]
        return JF, Z, rhoF, res0, res1, r1s, duals, Y0F

    from sagecal_tpu.compat import shard_map
    spec_f = P(axis)
    spec_r = P()
    nin = 8 + (1 if dobeam else 0)     # beam pytree rides a prefix spec
    if not host_loop:
        prog = shard_map(
            admm_program, mesh=mesh,
            in_specs=(spec_f,) * nin,
            out_specs=(spec_f, spec_r, spec_f, spec_f, spec_f,
                       P(None, axis), spec_r, spec_f),
            check_vma=False)
        return jax.jit(prog)

    # --- host-driven ADMM loop: one bounded device execution per ADMM
    # iteration (the tunneled single-chip runtime kills executions over
    # ~60 s; a fully traced n_admm-iteration program over folded subbands
    # exceeds it — and this is also the natural structure for streaming
    # telemetry per iteration, like the master's per-iter prints).
    carry_specs = (spec_f, spec_f, spec_r, spec_f, spec_f, spec_f,
                   spec_r, spec_r, spec_f)

    def iter0_flat(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                   *beam_rest):
        carry, res0, res1, Y0F = iter0_local(
            x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
            beam_rest[0] if beam_rest else None)
        return carry + (res0, res1, Y0F)

    def body_flat(x8F, uF, vF, wF, freqF, wtF, JF, YF, Z, rhoF, Yhat,
                  Jprev, Zbar, Xd, rho_upper, it, *beam_rest):
        carry = (JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd, rho_upper)
        carry, (r0, r1, dual) = body_local(
            x8F, uF, vF, wF, freqF, wtF, carry, it,
            beam_rest[0] if beam_rest else None)
        return carry + (r0, r1, dual)

    beam_specs = (spec_f,) if dobeam else ()
    prog0 = jax.jit(shard_map(
        iter0_flat, mesh=mesh, in_specs=(spec_f,) * 8 + beam_specs,
        out_specs=carry_specs + (spec_f, spec_f, spec_f),
        check_vma=False))
    # the ADMM carry (J/Y/Z/rho accumulators + BB state) is DONATED to
    # each body execution: every iteration rebinds the carry from the
    # program's outputs, so XLA reuses the buffers in place instead of
    # allocating a fresh accumulator set per ADMM iteration
    progb = jax.jit(shard_map(
        body_flat, mesh=mesh,
        in_specs=(spec_f,) * 6 + carry_specs + (spec_r,) + beam_specs,
        out_specs=carry_specs + (spec_f, spec_f, spec_r),
        check_vma=False),
        donate_argnums=tuple(range(6, 15)) if donate else ())

    # consensus-only program: everything one ADMM body iteration does
    # AFTER the J-update solves (z-sum psum, Bii solve, duals, BB rho),
    # as its own mesh execution — the measured collective-overhead
    # probe. Never donated: the caller times it repeatedly on one carry.
    def cons_flat(Jr, r0, r1, JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd,
                  rho_upper, it):
        carry = (JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd, rho_upper)
        carry, (r0o, r1o, dual) = body_post(Jr, r0, r1, carry, it)
        return carry + (r0o, r1o, dual)

    prog_cons = jax.jit(shard_map(
        cons_flat, mesh=mesh,
        in_specs=(spec_f, spec_f, spec_f) + carry_specs + (spec_r,),
        out_specs=carry_specs + (spec_f, spec_f, spec_r),
        check_vma=False))

    n_runs = [0]    # runner invocation ordinal = interval, for traces

    import time as _time

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    def run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F, *beam_rest):
        interval = n_runs[0]
        n_runs[0] += 1
        t0 = _time.perf_counter()
        out = prog0(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F,
                    *beam_rest)
        _t("iter0", t0, out[0])
        carry, (res0, res1, Y0F) = out[:9], out[9:]
        # per-iteration convergence records are DEFERRED: the means are
        # dispatched on device here (gated, cheap) and fetched in ONE
        # batched transfer after the loop, so tracing never inserts a
        # per-iteration host sync into the ADMM chain
        pend = []
        if dtrace.active() or obs.active():
            pend.append((0, jnp.mean(res1), None, jnp.mean(carry[3])))
        r1s, duals = [], []
        for it in range(1, max(cfg.n_admm, 1)):
            t0 = _time.perf_counter()
            out = progb(x8F, uF, vF, wF, freqF, wtF, *carry,
                        jnp.asarray(it, jnp.int32), *beam_rest)
            _t(f"body[{it}]", t0, out[0])
            carry, (_, r1, dual) = out[:9], out[9:]
            r1s.append(r1)
            duals.append(dual)
            if dtrace.active() or obs.active():
                pend.append((it, jnp.mean(r1), dual,
                             jnp.mean(carry[3])))
        _emit_deferred(pend, interval)
        JF, Z, rhoF = carry[0], carry[2], carry[3]
        F = x8F.shape[0]
        r1s_a = (jnp.stack(r1s) if r1s
                 else jnp.zeros((0, F), x8F.dtype))
        duals_a = (jnp.stack(duals) if duals
                   else jnp.zeros((0,), x8F.dtype))
        return JF, Z, rhoF, res0, res1, r1s_a, duals_a, Y0F

    run.consensus_program = prog_cons
    return run


def pad_time(arrays, nt: int, ndev_t: int, axis: int = 1):
    """THE padding contract for the time axis of the 2-D mesh, mirror
    of :func:`pad_subbands`: pad ``axis`` (the solution-interval axis)
    of every host array up to ``tpad = ceil(nt/ndev_t)*ndev_t`` by
    replicating the LAST interval — padded intervals solve numerically
    tame copies whose outputs the caller drops ([:nt] on the time
    axis). Unlike padded subbands they need no collective mask: the
    time axis carries no collective, every interval's consensus is its
    own freq-psum."""
    ndev_t = max(int(ndev_t), 1)
    tpad = -(-max(nt, ndev_t) // ndev_t) * ndev_t
    if tpad == nt:
        return list(arrays), tpad
    out = []
    for a in arrays:
        a = np.asarray(a)
        last = np.take(a, [-1], axis=axis)
        reps = np.concatenate([last] * (tpad - nt), axis=axis)
        out.append(np.concatenate([a, reps], axis=axis))
    return out, tpad


def divergence_reset(JF, J0F, res0, res_fin, ratio: float = 5.0):
    """The per-subband warm-start divergence rule (slave :680-683, the
    cli_mpi host-loop rule) as a traced op: a subband whose final ADMM
    residual is non-finite, exactly zero (all-flagged) or blew past
    ``ratio`` x its initial residual restarts the next interval from
    ``J0F`` instead of carrying its diverged Jones forward."""
    bad = (~jnp.isfinite(res_fin)) | (res_fin == 0.0) \
        | (res_fin > ratio * res0)
    return jnp.where(bad[:, None, None, None, None], J0F, JF)


def make_admm_runner_2d(dsky, sta1, sta2, cidx, cmask, n_stations: int,
                        fdelta: float, B_poly: np.ndarray,
                        cfg: ADMMConfig, mesh: Mesh, nf_total: int,
                        nt_total: int, with_shapelets: bool = False,
                        nbase: int | None = None,
                        host_loop: bool = False,
                        timer: list | None = None):
    """Consensus ADMM over a 2-D ``('freq', 'time')`` mesh: subbands
    shard on the freq axis exactly as :func:`make_admm_runner`, and
    the solution intervals shard on the time axis with the PR 2
    ``[tilesz, nbase]`` ``row_period`` tile as the shard unit — an
    F-subband x T-interval pod slice solves the whole observation as
    ONE SPMD program.

    Structure (MIGRATION.md "2-D mesh"):

    - per-interval SAGE/LM J-updates are independent along time: every
      (subband, interval) cell solves shard-local;
    - the polynomial-in-frequency consensus update (z-sum psum + Bii
      solve + duals) is a **freq-axis collective**: each interval owns
      its own Z, so time shards run the identical iteration schedule
      with no cross-time communication at all;
    - the warm-start J chain becomes a **time-axis scan seam**: each
      time shard scans its local contiguous block of intervals in
      order (interval t+1 warm-starts from t's Jones, with the
      divergence-reset rule in-program), and the FIRST interval of
      each block cold-starts from ``J0F`` — the one deliberate
      numerical deviation from the sequential chain, gated by the
      residual-parity envelope at bank time (MESH2D record).

    Dtype policy: identical contract to the 1-D mesh runner — ``x8``
    and ``wt`` may arrive in the reduced storage dtype and
    ``cfg.sage.dtype_policy`` rides into every sagefit; the consensus
    state never quantizes. There is no f32 fallback on this path.

    ``mesh`` must carry exactly the axes ``("freq", "time")``. Interval
    mapping: time-device d owns the contiguous block
    ``[d*Tl, (d+1)*Tl)`` where ``Tl = Tpad // ndev_time``.

    ``run(x8FT, uFT, vFT, wFT, freqF, wtFT, fratioFT, J0F)`` takes
    HOST arrays (it owns its staging, unlike the 1-D runner):
    ``[Fpad, Tpad, ...]`` per-cell data, ``freqF [Fpad]``, ``J0F
    [Fpad, M, K, N, 8]``; subband padding via :func:`pad_subbands`,
    time padding via :func:`pad_time`. Returns
    ``(JT, ZT, rhoT, res0T, res1T, r1sT, dualsT, Y0T)`` with a leading
    GLOBAL time axis: ``JT [Tpad, Fpad, M, K, N, 8]``, ``ZT [Tpad, M,
    P, K, N, 8]``, ``res* [Tpad, Fpad]``, ``r1sT [Tpad, n_admm-1,
    Fpad]``, ``dualsT [Tpad, n_admm-1]``.

    ``host_loop=True`` executes one bounded mesh program per time
    WAVEFRONT (wavefront w = interval ``d*Tl + w`` on every time
    device d, the warm-start carry rebound on the host between
    executions) — identical math to the fully traced scan, per-
    execution ``timer`` telemetry like the 1-D host loop. The runner
    exposes ``run.consensus_program`` (the per-iteration consensus
    half on the 2-D mesh) for the collective-overhead probe either
    way.

    Not offered here (use the 1-D runner): ``-X`` spatial
    regularization and ``-B`` beam tables (per-interval beam staging
    across the time mesh is future work; cli_mpi refuses the combo).
    """
    if cfg.spatialreg is not None:
        raise ValueError("2-D mesh runner does not support -X spatial "
                         "regularization; use make_admm_runner")
    if tuple(mesh.axis_names) != ("freq", "time"):
        raise ValueError(f"make_admm_runner_2d needs a ('freq', 'time') "
                         f"mesh, got axes {mesh.axis_names}")
    ndev_f, ndev_t = mesh.devices.shape
    parts = make_admm_runner(
        dsky, sta1, sta2, cidx, cmask, n_stations, fdelta, B_poly, cfg,
        mesh, nf_total, with_shapelets=with_shapelets, nbase=nbase,
        _return_parts=True)
    lsp = parts["local_solve_plain"]
    lsa = parts["local_solve_admm"]
    iter0_post = parts["iter0_post"]
    body_post = parts["body_post"]
    _brow = parts["_brow"]
    _per_subband = parts["_per_subband"]

    def one_interval(Jc, x8t, ut, vt, wt_, wtt, frt, freqF, J0F):
        """One solution interval's FULL ADMM chain on the local freq
        shard ([Fl, ...] arrays): iteration 0 + n_admm-1 body
        iterations, every consensus step a freq-axis collective.
        Returns (Jnext, outputs) — Jnext is the warm-start carry for
        the next interval in this time shard's block."""
        JF, res0, res1 = _per_subband(lsp)(x8t, ut, vt, wt_, wtt, Jc,
                                           freqF)
        carry, res0, res1, Y0F = iter0_post(JF, res0, res1, frt)
        Fl = x8t.shape[0]

        def body(carry, it):
            Brow = _brow(Fl)
            BZ = jnp.einsum("fp,mpknr->fmknr", Brow, carry[2])
            Jr, r0, r1 = _per_subband(lsa)(
                x8t, ut, vt, wt_, wtt, carry[0], freqF, carry[1], BZ,
                carry[3])
            return body_post(Jr, r0, r1, carry, it)

        carry, (r0s, r1s, duals) = jax.lax.scan(
            body, carry, jnp.arange(1, max(cfg.n_admm, 1),
                                    dtype=jnp.int32))
        JF, Z, rhoF = carry[0], carry[2], carry[3]
        res_fin = r1s[-1] if cfg.n_admm > 1 else res1
        Jnext = divergence_reset(JF, J0F, res0, res_fin)
        return Jnext, (JF, Z, rhoF, res0, res1, r1s, duals, Y0F)

    def scan_program(x8, u, v, w, freqF, wtf, fratio, J0F):
        # local shard: [Fl, Tl, ...]; scan the time block in order so
        # the warm-start chain is sequential WITHIN the shard
        xs = tuple(jnp.moveaxis(a, 1, 0)
                   for a in (x8, u, v, w, wtf, fratio))

        def step(Jc, per_t):
            x8t, ut, vt, wt_, wtt, frt = per_t
            return one_interval(Jc, x8t, ut, vt, wt_, wtt, frt, freqF,
                                J0F)

        _, outs = jax.lax.scan(step, J0F, xs)
        return outs

    def wave_program(x8, u, v, w, freqF, wtf, fratio, J0F, Jc):
        # local shard: [Fl, 1, ...] (one interval per time device per
        # wavefront); squeeze the unit time axis, run the interval,
        # re-emit with it so the out specs shard back over "time"
        sq = [a[:, 0] for a in (x8, u, v, w, wtf)]
        Jnext, outs = one_interval(Jc[:, 0], sq[0], sq[1], sq[2], sq[3],
                                   sq[4], fratio[:, 0], freqF, J0F)
        outs = tuple(o[None] for o in outs)     # leading local-time 1
        return (Jnext[:, None],) + outs

    from sagecal_tpu.compat import shard_map
    Pft = P("freq", "time")
    Pf = P("freq")
    # outputs stack a leading local-time axis: [Tl, ...]
    out_specs = (P("time", "freq"),            # JF
                 P("time"),                    # Z
                 P("time", "freq"),            # rhoF
                 P("time", "freq"),            # res0
                 P("time", "freq"),            # res1
                 P("time", None, "freq"),      # r1s
                 P("time"),                    # duals
                 P("time", "freq"))            # Y0F
    in_specs = (Pft, Pft, Pft, Pft, Pf, Pft, Pft, Pf)

    prog_scan = jax.jit(shard_map(
        scan_program, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs, check_vma=False))
    prog_wave = jax.jit(shard_map(
        wave_program, mesh=mesh, in_specs=in_specs + (Pft,),
        out_specs=(Pft,) + out_specs, check_vma=False))

    # the consensus half of one body iteration as its OWN 2-D mesh
    # program (the measured collective-overhead probe, multichip
    # precedent): every time shard runs its interval's freq-psum
    # consensus concurrently — exactly the per-iteration communication
    # pattern of the 2-D program. Carries are [Fpad, ...] arrays
    # replicated along "time".
    carry_specs = (Pf, Pf, P(), Pf, Pf, Pf, P(), P(), Pf)

    def cons_flat(Jr, r0, r1, JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd,
                  rho_upper, it):
        carry = (JF, YF, Z, rhoF, Yhat, Jprev, Zbar, Xd, rho_upper)
        carry, (r0o, r1o, dual) = body_post(Jr, r0, r1, carry, it)
        return carry + (r0o, r1o, dual)

    prog_cons = jax.jit(shard_map(
        cons_flat, mesh=mesh,
        in_specs=(Pf, Pf, Pf) + carry_specs + (P(),),
        out_specs=carry_specs + (Pf, Pf, P()),
        check_vma=False))

    sh_ft = NamedSharding(mesh, Pft)
    sh_f = NamedSharding(mesh, Pf)

    import time as _time

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    def run(x8FT, uFT, vFT, wFT, freqF, wtFT, fratioFT, J0F):
        x8FT, uFT, vFT, wFT, wtFT, fratioFT = [
            np.asarray(a) for a in (x8FT, uFT, vFT, wFT, wtFT,
                                    fratioFT)]
        Fpad, Tpad = x8FT.shape[:2]
        if Fpad % ndev_f or Tpad % ndev_t:
            raise ValueError(
                f"staged axes [F={Fpad}, T={Tpad}] must divide the "
                f"mesh {ndev_f}x{ndev_t} (pad_subbands / pad_time)")
        if Tpad < -(-nt_total // ndev_t) * ndev_t:
            raise ValueError(
                f"staged time axis {Tpad} cannot hold the declared "
                f"{nt_total} intervals over {ndev_t} time devices "
                f"(pad_time)")
        freq_d = jax.device_put(np.asarray(freqF), sh_f)
        J0_d = jax.device_put(np.asarray(J0F), sh_f)
        if not host_loop:
            t0 = _time.perf_counter()
            args_d = [jax.device_put(a, sh_ft)
                      for a in (x8FT, uFT, vFT, wFT)]
            wt_d = jax.device_put(wtFT, sh_ft)
            fr_d = jax.device_put(fratioFT, sh_ft)
            out = prog_scan(args_d[0], args_d[1], args_d[2], args_d[3],
                            freq_d, wt_d, fr_d, J0_d)
            _t("scan", t0, out[0])
            return out

        # wavefront host loop: one bounded execution per local
        # interval index w; time-device d solves interval d*Tl + w
        Tl = Tpad // ndev_t
        outs_host = [None] * 8

        def _place(buf, w, a, t_lead):
            # a: wavefront output with time axis leading (t_lead) or
            # second; scatter device d's cell to interval d*Tl + w
            a = np.asarray(a)
            at = a if t_lead else np.moveaxis(a, 1, 0)
            if buf is None:
                buf = np.zeros((Tpad,) + at.shape[1:], at.dtype)
            buf[w::Tl] = at
            return buf

        Jc = np.broadcast_to(
            np.asarray(J0F)[:, None],
            (Fpad, ndev_t) + np.asarray(J0F).shape[1:])
        Jc_d = jax.device_put(np.ascontiguousarray(Jc), sh_ft)
        for w in range(Tl):
            t0 = _time.perf_counter()
            sl = [jax.device_put(np.ascontiguousarray(a[:, w::Tl]),
                                 sh_ft)
                  for a in (x8FT, uFT, vFT, wFT, wtFT, fratioFT)]
            out = prog_wave(sl[0], sl[1], sl[2], sl[3], freq_d, sl[4],
                            sl[5], J0_d, Jc_d)
            _t(f"wave[{w}]", t0, out[0])
            Jc_d = out[0]
            # wavefront outputs: JF/rho/res/r1s/Y0 lead with the local
            # time axis (size 1 per device -> global [ndev_t, ...])
            for i, o in enumerate(out[1:]):
                outs_host[i] = _place(outs_host[i], w, o, t_lead=True)
        return tuple(jnp.asarray(b) for b in outs_host)

    run.consensus_program = prog_cons
    run.mesh_shape = (ndev_f, ndev_t)
    return run


def make_admm_runner_stale(dsky, sta1, sta2, cidx, cmask,
                           n_stations: int, fdelta: float,
                           B_poly: np.ndarray, cfg: ADMMConfig,
                           nf_total: int, staleness: int = 0,
                           with_shapelets: bool = False,
                           nbase: int | None = None, device=None,
                           timer: list | None = None):
    """Bounded-staleness consensus ADMM (opt-in): a straggling subband
    may SKIP its J-update for a round while every other subband keeps
    iterating against its last-sent dual contribution — consumed up to
    ``staleness`` iterations stale — instead of the whole pod pacing
    on the slowest subband (arXiv:1605.09219's stale-tolerant rho
    schedules; arXiv:1410.2101's ADI analysis of reordered updates).

    Composition with the PR 9 fault harness makes the straggler a
    MEASURED experiment rather than a hang: per round, each subband
    asks ``faults.fires("admm_subband_slow", key=f)`` whether it is
    slow — but only when skipping would keep its staleness within the
    bound (``staleness=0`` never even asks: the synchronous chain).
    A subband whose bound is exhausted is forced to update — the
    simulation analogue of the synchronous runner blocking on it, so
    the chain NEVER deadlocks on a slow subband, and a ``kind:
    "fatal"`` rule marks the subband DEAD: it is masked out of every
    later consensus like a padded mesh slot (zero rho, zero sent
    dual) and its last residual is carried forward.

    Semantics per round (vs the synchronous body_post):

    - updated subbands: ``Ysent_f = Y_f + rho_f J_f(new)`` then the
      dual step against the fresh Z, exactly the synchronous math;
    - sleeping subbands: ``Ysent_f`` (their last-sent contribution)
      enters the z-sum unchanged — the "stale dual" — and their
      ``Y_f``/``J_f``/residual are untouched;
    - the Z solve itself stays exact over the mixed-freshness table.

    With ``staleness=0`` — or any bound but no fault plan — every
    subband updates every round and the chain is BIT-IDENTICAL to
    ``make_admm_runner_blocked(block_f=1)`` (gated,
    tests/test_mesh2d.py). ``adaptive_rho`` is refused: BB steps over
    mixed-staleness increments have no convergence story.

    Single-device host-driven execution (block_f=1 per-subband
    executions — the granularity that lets a real deployment actually
    skip a straggler's solve). Same run signature/outputs as
    :func:`make_admm_runner_blocked`; additionally ``run.schedule``
    holds, per interval, the list of per-round update masks and
    ``run.dead`` the dead-subband set — the harness's telemetry.
    """
    import time as _time

    from sagecal_tpu import faults

    if cfg.spatialreg is not None:
        raise ValueError("bounded-staleness runner does not support -X "
                         "spatial regularization")
    if cfg.adaptive_rho:
        raise ValueError("bounded-staleness consensus requires "
                         "adaptive_rho=False (BB rho over stale "
                         "increments is undefined)")
    S = int(staleness)
    if S < 0:
        raise ValueError(f"staleness {S}: must be >= 0")

    devs = [device] if device is not None else jax.devices()[:1]
    mesh = Mesh(np.array(devs), ("freq",))
    parts = make_admm_runner(
        dsky, sta1, sta2, cidx, cmask, n_stations, fdelta, B_poly, cfg,
        mesh, nf_total, with_shapelets=with_shapelets, nbase=nbase,
        _return_parts=True)
    local_solve_plain = parts["local_solve_plain"]
    local_solve_admm = parts["local_solve_admm"]
    iter0_post = parts["iter0_post"]
    body_post = parts["body_post"]
    _brow = parts["_brow"]
    _per_subband = parts["_per_subband"]

    M = int(np.asarray(cmask).shape[0])
    K = int(np.asarray(cmask).shape[1])
    N = n_stations

    solve0 = jax.jit(_per_subband(local_solve_plain))
    solveb = jax.jit(_per_subband(local_solve_admm))
    cons0 = jax.jit(lambda JF, res0, res1, fratioF: iter0_post(
        JF, res0, res1, fratioF, ax=None), donate_argnums=(0,))
    bz_prog = jax.jit(
        lambda Z, Brow: jnp.einsum("fp,mpknr->fmknr", Brow, Z))

    def stale_post(Jr, r1_new, upd, alive, JF, YF, Z, rhoF, Ysent,
                   r1_prev, it):
        """The consensus half of one stale round. ``upd``/``alive``:
        [F] {0,1} masks. With upd == alive == 1 everywhere this
        computes bit-for-bit the synchronous ``body_post`` values
        (the where() wrappers select the identical branch
        expressions), which is the S=0 parity gate's contract."""
        F = Jr.shape[0]
        dtype = Jr.dtype
        Brow = _brow(F, None)
        J5 = Jr.reshape(F, M, K, N, 8)
        upd5 = upd[:, None, None, None, None]
        alive5 = alive[:, None, None, None, None]
        rho_eff = jnp.where(alive[:, None] > 0, rhoF, 0.0)
        Ysent = jnp.where(upd5 > 0,
                          YF + rho_eff[..., None, None, None] * J5,
                          Ysent)
        Ysent = jnp.where(alive5 > 0, Ysent, 0.0)
        Zold = Z
        zsum = jnp.einsum("fp,fmknr->mpknr", Brow, Ysent)
        Bii = cpoly.find_prod_inverse(
            parts["Bfull"], rho_eff.T.astype(Ysent.dtype))
        Z = cpoly.z_from_contributions(zsum, Bii)
        BZn = jnp.einsum("fp,mpknr->fmknr", Brow, Z)
        YF = jnp.where(upd5 > 0,
                       Ysent - rho_eff[..., None, None, None] * BZn, YF)
        JF = jnp.where(upd5 > 0, J5.reshape(JF.shape), JF)
        r1 = jnp.where(upd > 0, r1_new, r1_prev)
        dual = jnp.linalg.norm(Z - Zold) / np.sqrt(Z.size)
        return JF, YF, Z, rho_eff, Ysent, r1, dual

    stale_cons = jax.jit(stale_post)

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    n_runs = [0]
    schedule: list = []
    dead_log: list = []

    def run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F, *beam_rest):
        if beam_rest:
            raise ValueError("bounded-staleness runner does not "
                             "support -B beam tables")
        interval = n_runs[0]
        n_runs[0] += 1
        F = x8F.shape[0]
        Brow_full = _brow(F, None)

        def take(a, f):
            return jax.tree.map(lambda x: x[f:f + 1], a)

        def sub_solve0(f):
            t0 = _time.perf_counter()
            Jb, r0b, r1b = solve0(take(x8F, f), take(uF, f), take(vF, f),
                                  take(wF, f), take(wtF, f),
                                  take(J0F, f), take(freqF, f))
            _t(f"solve0[{f}]", t0, Jb)
            return Jb, r0b, r1b

        def sub_solveb(f, JF, YF, BZ, rhoF):
            t0 = _time.perf_counter()
            Jb, r0b, r1b = solveb(take(x8F, f), take(uF, f), take(vF, f),
                                  take(wF, f), take(wtF, f),
                                  take(JF, f), take(freqF, f),
                                  take(YF, f), take(BZ, f),
                                  take(rhoF, f))
            _t(f"solve[{f}]", t0, Jb)
            return Jb, r0b, r1b

        # --- iteration 0: synchronous for every subband (the dual
        # seed + manifold averaging need the full subband set)
        Js, r0s, r1s_l = zip(*[sub_solve0(f) for f in range(F)])
        JF = jnp.concatenate(Js)
        res0 = jnp.concatenate(r0s)
        res1 = jnp.concatenate(r1s_l)
        t0 = _time.perf_counter()
        carry, res0, res1, Y0F = cons0(JF, res0, res1, fratioF)
        _t("cons0", t0, carry[2])
        JF, YF, Z, rhoF = carry[0], carry[1], carry[2], carry[3]
        # last-sent table: iteration 0's sent contribution is the
        # manifold-projected rho*J — exactly Y0F
        Ysent = Y0F
        r1_cur = res1

        alive_np = np.ones(F, np.float64)
        alive_np[nf_total:] = 0.0          # padded mesh slots
        upd_base = alive_np.copy()
        last_update = np.zeros(F, np.int64)
        dead: set = set()
        sched_rounds: list = []
        r1h, dualh, pend = [], [], []
        for it in range(1, max(cfg.n_admm, 1)):
            upd_np = upd_base.copy()
            for f in range(min(nf_total, F)):
                if f in dead:
                    upd_np[f] = 0.0
                    continue
                # may f be lazy this round? only asked when the bound
                # permits the resulting staleness
                if S > 0 and (it - last_update[f]) <= S:
                    kind = faults.draw("admm_subband_slow", key=f)
                    if kind == "fatal":
                        dead.add(f)
                        alive_np[f] = 0.0
                        upd_base[f] = 0.0
                        upd_np[f] = 0.0
                        dead_log.append((interval, it, f))
                        continue
                    if kind is not None:
                        upd_np[f] = 0.0
                        continue
                last_update[f] = it
            sched_rounds.append(upd_np.copy())

            BZ = bz_prog(Z, Brow_full)
            Jr = JF
            r1_new = r1_cur
            for f in range(F):
                if upd_np[f] == 0.0:
                    continue
                Jb, _r0b, r1b = sub_solveb(f, JF, YF, BZ, rhoF)
                # in-place-style scatter: one dispatch per subband,
                # no full-[F] copies (the values land verbatim, so
                # the S=0 bit-identity gate is untouched)
                Jr = Jr.at[f:f + 1].set(Jb)
                r1_new = r1_new.at[f:f + 1].set(r1b)
            upd_d = jnp.asarray(upd_np, JF.dtype)
            alive_d = jnp.asarray(alive_np, JF.dtype)
            t0 = _time.perf_counter()
            JF, YF, Z, rhoF, Ysent, r1_cur, dual = stale_cons(
                Jr, r1_new, upd_d, alive_d, JF, YF, Z, rhoF, Ysent,
                r1_cur, jnp.asarray(it, jnp.int32))
            _t(f"cons[{it}]", t0, Z)
            r1h.append(r1_cur)
            dualh.append(dual)
            if dtrace.active() or obs.active():
                pend.append((it, jnp.mean(r1_cur), dual,
                             jnp.mean(rhoF)))
                skipped = [f for f in range(nf_total)
                           if upd_np[f] == 0.0]
                if skipped:
                    dtrace.emit("admm_stale", interval=interval,
                                iter=it, skipped=skipped,
                                dead=sorted(dead))
        _emit_deferred(pend, interval)
        schedule.append(sched_rounds)
        r1s_a = (jnp.stack(r1h) if r1h
                 else jnp.zeros((0, F), x8F.dtype))
        duals_a = (jnp.stack(dualh) if dualh
                   else jnp.zeros((0,), x8F.dtype))
        return JF, Z, rhoF, res0, res1, r1s_a, duals_a, Y0F

    run.schedule = schedule
    run.dead = dead_log
    return run


def make_admm_runner_blocked(dsky, sta1, sta2, cidx, cmask,
                             n_stations: int, fdelta: float,
                             B_poly: np.ndarray, cfg: ADMMConfig,
                             nf_total: int, block_f: int,
                             with_shapelets: bool = False,
                             dobeam: int = 0, nbase: int | None = None,
                             device=None, timer=None):
    """Single-device consensus ADMM with the J-update split into subband
    BLOCKS of ``block_f`` — one bounded device execution per block, tiny
    consensus executions in between. Identical math to
    :func:`make_admm_runner` (it reuses the same iter0_post/body_post
    consensus code with ax=None), built for shapes where one folded
    J-update over all subbands would exceed the tunneled chip's
    per-execution wall-clock kill (~60 s): the north-star 64-station x
    100-direction x 32-subband problem.

    Spatial regularization is not offered here (use the mesh runner).
    ``timer``: optional list that receives (label, seconds) tuples for
    per-execution telemetry.
    """
    import time as _time

    if cfg.spatialreg is not None:
        raise ValueError("blocked runner does not support -X spatial "
                         "regularization; use make_admm_runner")
    # borrow the full closure set from make_admm_runner on a 1-device
    # mesh; we only use its ax=None entry points, never its shard_map
    # programs
    devs = [device] if device is not None else jax.devices()[:1]
    mesh = Mesh(np.array(devs), ("freq",))
    parts = make_admm_runner(
        dsky, sta1, sta2, cidx, cmask, n_stations, fdelta, B_poly, cfg,
        mesh, nf_total, with_shapelets=with_shapelets,
        dobeam=dobeam, nbase=nbase,
        _return_parts=True)
    local_solve_plain = parts["local_solve_plain"]
    local_solve_admm = parts["local_solve_admm"]
    iter0_post = parts["iter0_post"]
    body_post = parts["body_post"]
    _brow = parts["_brow"]

    # the shared unit-width wrapper: block_f == 1 (the north-star's
    # best plan) takes the axis-free call, avoiding the unit-vmap
    # layout penalty
    _per_subband = parts["_per_subband"]
    solve0 = jax.jit(_per_subband(local_solve_plain))
    solveb = jax.jit(_per_subband(local_solve_admm))
    # donate the block-solved Jones and the ADMM carry into the
    # consensus steps (same in-place reuse as make_admm_runner's
    # host-loop donation; callers rebind both from the outputs)
    cons0 = jax.jit(lambda JF, res0, res1, fratioF: iter0_post(
        JF, res0, res1, fratioF, ax=None), donate_argnums=(0,))
    consb = jax.jit(lambda Jr, r0, r1, carry, it: body_post(
        Jr, r0, r1, carry, it, ax=None), donate_argnums=(0, 3))
    bz_prog = jax.jit(
        lambda Z, Brow: jnp.einsum("fp,mpknr->fmknr", Brow, Z))

    def _t(label, t0, out):
        if timer is not None:
            jax.block_until_ready(out)
            timer.append((label, _time.perf_counter() - t0))
        return out

    n_runs = [0]    # runner invocation ordinal = interval, for traces

    def run(x8F, uF, vF, wF, freqF, wtF, fratioF, J0F, *beam_rest):
        interval = n_runs[0]
        n_runs[0] += 1
        beamF = beam_rest[0] if beam_rest else None
        F = x8F.shape[0]
        Brow_full = _brow(F, None)          # eager: Bfull[:F]
        blocks = [slice(b, min(b + block_f, F))
                  for b in range(0, F, block_f)]

        def take(a, sl):
            """Block slice, padded to block_f by repeating the first
            row so every block compiles to ONE program shape (a ragged
            tail block would otherwise double the solve compiles)."""
            ab = a[sl]
            short = block_f - ab.shape[0]
            if short:
                ab = jnp.concatenate(
                    [ab, jnp.broadcast_to(ab[:1],
                                          (short,) + ab.shape[1:])])
            return ab

        # constant per-tile inputs: slice/pad each block ONCE, not per
        # ADMM iteration
        const_blocks = [tuple(take(a, sl)
                              for a in (x8F, uF, vF, wF, wtF, freqF))
                        for sl in blocks]
        beam_blocks = None
        if beamF is not None:
            beam_blocks = [jax.tree.map(lambda a: take(a, sl), beamF)
                           for sl in blocks]

        def blockwise(fn, *per_iter):
            """fn(x8, u, v, w, wt, freq, *per-iteration block args)."""
            Js, r0s, r1s = [], [], []
            for i, sl in enumerate(blocks):
                t0 = _time.perf_counter()
                bb = (beam_blocks[i],) if beam_blocks is not None else ()
                Jb, r0b, r1b = fn(*const_blocks[i],
                                  *[take(a, sl) for a in per_iter], *bb)
                _t(f"solve[{i}]", t0, Jb)
                nreal = sl.stop - sl.start
                Js.append(Jb[:nreal])
                r0s.append(r0b[:nreal])
                r1s.append(r1b[:nreal])
            return (jnp.concatenate(Js), jnp.concatenate(r0s),
                    jnp.concatenate(r1s))

        def solve0_re(x8, u, v, w, wt, freq, J0, *bb):
            return solve0(x8, u, v, w, wt, J0, freq, *bb)

        def solveb_re(x8, u, v, w, wt, freq, J, Y, BZ, rho, *bb):
            return solveb(x8, u, v, w, wt, J, freq, Y, BZ, rho, *bb)

        JF, res0, res1 = blockwise(solve0_re, J0F)
        t0 = _time.perf_counter()
        carry, res0, res1, Y0F = cons0(JF, res0, res1, fratioF)
        _t("cons0", t0, carry[2])
        r1h, dualh = [], []
        pend = []       # deferred admm_iter records (no per-iter sync)
        for it in range(1, max(cfg.n_admm, 1)):
            BZ = bz_prog(carry[2], Brow_full)
            Jr, r0, r1 = blockwise(solveb_re, carry[0], carry[1], BZ,
                                   carry[3])
            t0 = _time.perf_counter()
            carry, (r0, r1, dual) = consb(Jr, r0, r1, carry,
                                          jnp.asarray(it, jnp.int32))
            _t(f"cons[{it}]", t0, carry[2])
            r1h.append(r1)
            dualh.append(dual)
            if dtrace.active() or obs.active():
                pend.append((it, jnp.mean(r1), dual,
                             jnp.mean(carry[3])))
        _emit_deferred(pend, interval)
        JF, Z, rhoF = carry[0], carry[2], carry[3]
        r1s_a = (jnp.stack(r1h) if r1h
                 else jnp.zeros((0, F), x8F.dtype))
        duals_a = (jnp.stack(dualh) if dualh
                   else jnp.zeros((0,), x8F.dtype))
        return JF, Z, rhoF, res0, res1, r1s_a, duals_a, Y0F

    return run
