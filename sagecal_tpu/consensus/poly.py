"""Frequency-polynomial consensus: basis, Z-update, adaptive rho.

Capability parity with reference ``src/lib/Dirac/consensus_poly.c``:
- ``setup_polynomials`` (:39): type 0/1 monomials in (f-f0)/f0 (type 1
  row-normalized), type 2 Bernstein on [fmin, fmax], type 3 alternating
  (f-f0)/f0 and (f0/f-1) powers;
- ``find_prod_inverse_full[_fed]`` (:460, :560): per-cluster pseudo-inverse
  of sum_f rho[k,f] B_f B_f^T (+ alpha I federated variant) via SVD;
- ``update_global_z_multi`` (:773): per-cluster Z = (sum_f B_f x z_f) Bi;
- ``soft_threshold_z`` (:1039);
- Barzilai-Borwein spectral rho adaptation ``update_rho_bb`` (:923) with
  the correlation/step heuristics of Xu et al.

All operations are batched dense linear algebra — on the mesh, the sum
over frequencies is a ``psum`` over the subband axis (SURVEY.md P10).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def setup_polynomials(freqs, freq0, npoly: int, ptype: int = 2) -> np.ndarray:
    """[Nf, Npoly] real basis matrix B (host-side, numpy)."""
    freqs = np.asarray(freqs, np.float64)
    nf = len(freqs)
    B = np.zeros((nf, npoly))
    if ptype in (0, 1):
        frat = (freqs - freq0) / freq0
        B[:, 0] = 1.0
        for p in range(1, npoly):
            B[:, p] = B[:, p - 1] * frat
        if ptype == 1:
            nrm = np.sqrt((B ** 2).sum(axis=0))
            B = B / np.where(nrm > 0, nrm, 1.0)
    elif ptype == 2:
        fmax, fmin = freqs.max(), freqs.min()
        x = (freqs - fmin) / max(fmax - fmin, 1e-30)
        from math import comb
        for p in range(npoly):
            B[:, p] = comb(npoly - 1, p) * x ** p * (1 - x) ** (npoly - 1 - p)
    elif ptype == 3:
        B[:, 0] = 1.0
        frat = (freqs - freq0) / freq0
        last = frat.copy()
        for p in range(1, npoly, 2):
            B[:, p] = last
            last = last * frat
        grat = freq0 / freqs - 1.0
        last = grat.copy()
        for p in range(2, npoly, 2):
            B[:, p] = last
            last = last * grat
    else:
        raise ValueError(f"undefined polynomial type {ptype}")
    return B


def find_prod_inverse(B, rho, alpha=None):
    """Per-cluster pinv(sum_f rho[k,f] B_f B_f^T [+ alpha_k I]) -> [M, P, P].

    B: [Nf, P]; rho: [M, Nf] (per-cluster per-freq regularization);
    alpha: optional [M] federated penalty (find_prod_inverse_full_fed).
    """
    B = jnp.asarray(B)
    outer = jnp.einsum("fp,fq->fpq", B, B)               # [Nf, P, P]
    S = jnp.einsum("mf,fpq->mpq", jnp.asarray(rho), outer)
    if alpha is not None:
        S = S + jnp.asarray(alpha)[:, None, None] * jnp.eye(B.shape[1])
    # SVD pseudo-inverse (sum_inv_threadfn, consensus_poly.c:301)
    U, s, Vt = jnp.linalg.svd(S)
    sinv = jnp.where(s > 1e-12 * s.max(axis=-1, keepdims=True), 1.0 / s, 0.0)
    return jnp.einsum("mqp,mq,mrq->mpr", Vt, sinv, U)


def z_from_contributions(zsum, Bi):
    """Global Z update: Z[k] = Bi[k] @ zsum[k] (update_global_z_multi).

    zsum: [M, P, ...] = sum_f B[f, p] * (Y_f + rho_f J_f)[...] — on a mesh
    this sum arrives via psum over the subband axis. Bi: [M, P, P].
    Returns Z [M, P, ...].
    """
    lead = zsum.shape[2:]
    flat = zsum.reshape(zsum.shape[0], zsum.shape[1], -1)
    Z = jnp.einsum("mpq,mqx->mpx", Bi, flat)
    return Z.reshape(zsum.shape[0], zsum.shape[1], *lead)


def bz(Z, Brow):
    """Evaluate the consensus polynomial at one frequency: sum_p B[f,p] Z_p.

    Z: [M, P, ...]; Brow: [P]. Returns [M, ...].
    """
    return jnp.tensordot(jnp.asarray(Brow), Z, axes=(0, 1))


def soft_threshold(Z, lam):
    """Elementwise soft threshold (consensus_poly.c:1039)."""
    return jnp.sign(Z) * jnp.maximum(jnp.abs(Z) - lam, 0.0)


def update_rho_bb(rho, rho_upper, dY, dJ, axes):
    """Barzilai-Borwein spectral rho (consensus_poly.c:923, Xu et al.).

    rho, rho_upper: [M]; dY = Yhat - Yhat_old, dJ = J - J_old with per-
    cluster parameter blocks; ``axes`` are the axes of dY/dJ to reduce over
    (everything except the cluster axis 0).

    Heuristics preserved: update only when correlation > 0.2 and
    0.001 < alphahat < rho_upper; alphahat = alphaMG if 2 alphaMG > alphaSD
    else alphaSD - alphaMG/2.
    """
    ip12 = jnp.sum(dY * dJ, axis=axes)
    ip11 = jnp.sum(dY * dY, axis=axes)
    ip22 = jnp.sum(dJ * dJ, axis=axes)
    eps = 1e-12
    corr = ip12 / jnp.sqrt(jnp.maximum(ip11 * ip22, eps))
    alpha_sd = ip11 / jnp.maximum(ip12, eps)
    alpha_mg = ip12 / jnp.maximum(ip22, eps)
    alphahat = jnp.where(2.0 * alpha_mg > alpha_sd, alpha_mg,
                         alpha_sd - 0.5 * alpha_mg)
    ok = ((ip12 > eps) & (ip11 > eps) & (ip22 > eps) & (corr > 0.2)
          & (alphahat > 0.001) & (alphahat < rho_upper))
    return jnp.where(ok, alphahat, rho)
