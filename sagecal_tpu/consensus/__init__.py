from sagecal_tpu.consensus import manifold as manifold
from sagecal_tpu.consensus import poly as poly
