"""Model-order selection for the consensus frequency polynomial.

Capability parity with reference ``src/lib/Dirac/mdl.c``
(``minimum_description_length``:42, the ``-M`` flag of sagecal-mpi): scan
polynomial orders K in [kstart, kfinish]; for each order estimate the
consensus Z from the per-subband (rho-weighted) solutions, measure the
residual sum of squares of the polynomial fit across frequency, and score

    AIC(K) = F log(RSS/F) + 2K
    MDL(K) = F/2 log(RSS/F) + K/2 log(F)

reporting the minimizing order of each (mdl.c:231-262).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from sagecal_tpu.consensus import poly as cpoly


def minimum_description_length(J, rho, freqs, freq0: float, weight=None,
                               polytype: int = 2, kstart: int = 1,
                               kfinish: int = 5):
    """Scan consensus polynomial orders and score them.

    J: [F, M, ...] per-subband rho-weighted solutions (the master's
       ``rho J`` vectors; any trailing shape — K, N, 8 — is flattened).
    rho: [M] per-cluster regularization.
    weight: [F] per-subband weights (flag ratios), default 1.

    Returns dict with ``orders``, ``aic``, ``mdl``, ``best_aic``,
    ``best_mdl``.
    """
    J = np.asarray(J, np.float64)
    F, M = J.shape[0], J.shape[1]
    rest = int(np.prod(J.shape[2:]))
    J = J.reshape(F, M, rest)
    rho = np.broadcast_to(np.asarray(rho, np.float64), (M,))
    weight = (np.ones(F) if weight is None
              else np.asarray(weight, np.float64))
    freqs = np.asarray(freqs, np.float64)

    inv_rho = np.where(rho > 0.0, 1.0 / np.maximum(rho, 1e-300), 0.0)
    orders = list(range(kstart, kfinish + 1))
    aic = np.zeros(len(orders))
    mdl = np.zeros(len(orders))
    for i, K in enumerate(orders):
        # constant polynomial always uses type 1 (mdl.c:127)
        B = cpoly.setup_polynomials(freqs, freq0, K,
                                    1 if K == 1 else polytype)    # [F, K]
        rho_w = np.tile(weight[None, :], (M, 1))                  # [M, F]
        Bii = np.asarray(cpoly.find_prod_inverse(jnp.asarray(B),
                                                 jnp.asarray(rho_w)))
        # z = sum_f B_f (J_f / rho)  (mdl.c:140-156)
        Jsc = J * inv_rho[None, :, None]
        zsum = np.einsum("fp,fmr->mpr", B, Jsc)
        Z = np.einsum("mpq,mqr->mpr", Bii, zsum)                  # [M, K, r]
        # residual of the fit: E_f = J_f/(rho w_f) - B_f Z (mdl.c:176-229)
        BZ = np.einsum("fp,mpr->fmr", B, Z)
        inv_w = np.where(weight > 0.0, 1.0 / np.maximum(weight, 1e-300), 0.0)
        E = Jsc * inv_w[:, None, None] - BZ
        # RSS per data point: mdl.c:230 divides by the 8NM block size
        rss = float(np.sum(E * E)) / (M * rest)
        aic[i] = F * np.log(max(rss / F, 1e-300)) + 2.0 * K
        mdl[i] = 0.5 * F * np.log(max(rss / F, 1e-300)) \
            + 0.5 * K * np.log(F)
    return {
        "orders": orders, "aic": aic, "mdl": mdl,
        "best_aic": orders[int(np.argmin(aic))],
        "best_mdl": orders[int(np.argmin(mdl))],
    }


def report(result, log=print):
    """mdl.c:265-266 summary line."""
    log(f"Finding best fitting polynomials: MDL "
        f"{result['mdl'].min():.6f} for polynomial terms="
        f"{result['best_mdl']}, AIC {result['aic'].min():.6f} "
        f"for polynomial terms={result['best_aic']}")
