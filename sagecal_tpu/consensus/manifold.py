"""Manifold averaging: resolving the per-frequency unitary ambiguity.

Capability parity with reference ``src/lib/Dirac/manifold_average.c``
(``calculate_manifold_average``:204, ``project_procrustes[_block]``:266/346):
per direction, each frequency's solution block (viewed as a 2N x 2 complex
matrix) is defined only up to a right 2x2 unitary; averaging across
frequency first rotates every block onto a reference block (Procrustes),
then iterates {mean -> project each block onto the mean}, and finally
applies exactly ONE unitary to each original block (manifold_average.c:
147-177) so solutions are modified only by a phase/unitary factor.

TPU re-architecture: the 2x2 complex SVD-based Procrustes factor
U V^H = polar(A) is computed with a closed-form 2x2 polar decomposition
(no LAPACK), fully batched over (direction, frequency) — and on the mesh
the frequency mean is a ``psum`` (SURVEY.md P10 "manifold averaging at
iter 0").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _herm_invsqrt_2x2(H, eps=1e-12):
    """Inverse square root of a 2x2 Hermitian PSD matrix, closed form.

    sqrt(H) = (H + sqrt(det) I) / sqrt(trace + 2 sqrt(det));
    inv via adjugate. Batched over leading axes.
    """
    t = H[..., 0, 0] + H[..., 1, 1]
    d = H[..., 0, 0] * H[..., 1, 1] - H[..., 0, 1] * H[..., 1, 0]
    sd = jnp.sqrt(jnp.maximum(d.real, 0.0)).astype(H.dtype)
    denom = jnp.sqrt(jnp.maximum((t + 2 * sd).real, eps)).astype(H.dtype)
    sq = (H + sd[..., None, None] * jnp.eye(2, dtype=H.dtype)) \
        / denom[..., None, None]
    det_sq = sq[..., 0, 0] * sq[..., 1, 1] - sq[..., 0, 1] * sq[..., 1, 0]
    det_sq = jnp.where(jnp.abs(det_sq) < eps, eps, det_sq)
    adj = jnp.stack([
        jnp.stack([sq[..., 1, 1], -sq[..., 0, 1]], -1),
        jnp.stack([-sq[..., 1, 0], sq[..., 0, 0]], -1),
    ], -2)
    return adj / det_sq[..., None, None]


def polar_unitary_2x2(A):
    """U V^H of the SVD of a 2x2 complex A == its polar unitary factor:
    A (A^H A)^(-1/2). Batched."""
    AH_A = jnp.einsum("...ji,...jk->...ik", jnp.conj(A), A)
    return A @ _herm_invsqrt_2x2(AH_A)


def procrustes_project(X, Y):
    """Rotate Y onto X: Y <- Y U, U = argmin ||X - Y U||_F over unitaries.

    X, Y: [..., 2N, 2] complex stacked solution blocks
    (project_procrustes_block, manifold_average.c:346). U = polar(Y^H X).
    """
    A = jnp.einsum("...ji,...jk->...ik", jnp.conj(Y), X)  # [..., 2, 2]
    return Y @ polar_unitary_2x2(A)


def jones_to_blocks(J):
    """[..., N, 2, 2] Jones -> [..., 2N, 2] stacked blocks X = [J_1; J_2; ...].

    With this stacking the per-frequency gauge freedom of the unpolarized
    calibration problem (J_p -> J_p U, same 2x2 unitary U for every
    station; V = J_p C J_q^H invariant when C is diagonal-dominated) is a
    RIGHT multiplication X -> X U, exactly what the Procrustes projection
    removes (the role of the reference's 2N x 2 J-format blocks,
    manifold_average.c:86-96).
    """
    return J.reshape(J.shape[:-3] + (2 * J.shape[-3], 2))


def blocks_to_jones(X):
    """Inverse of :func:`jones_to_blocks`."""
    n = X.shape[-2] // 2
    return X.reshape(X.shape[:-2] + (n, 2, 2))


def manifold_average(J, niter: int = 3, ref_index: int = 0):
    """Frequency-average solutions up to unitary ambiguity.

    J: [Nf, M, N, 2, 2] complex per-frequency per-direction Jones.
    Returns J with each (f, m) block replaced by the original block rotated
    by one unitary toward the cross-frequency average
    (calculate_manifold_average semantics; ``ref_index`` stands in for the
    reference's random initial block).

    Note: this host-mesh-agnostic version computes means over axis 0;
    in the distributed ADMM the same math runs with a psum.
    """
    X0 = jones_to_blocks(J)                        # [Nf, M, 2N, 2]
    nf = X0.shape[0]

    # initial alignment to the reference frequency's block
    ref = X0[ref_index]
    X = procrustes_project(ref[None], X0)

    # iterate mean -> project
    def body(X, _):
        mean = jnp.mean(X, axis=0, keepdims=True)
        return procrustes_project(mean, X), None
    X, _ = jax.lax.scan(body, X, None, length=niter)

    # final: ONE unitary applied to the original blocks, toward the mean
    mean = jnp.mean(X, axis=0, keepdims=True)
    Xout = procrustes_project(mean, X0)
    return blocks_to_jones(Xout)
