"""Manifold averaging: resolving the per-frequency unitary ambiguity.

Capability parity with reference ``src/lib/Dirac/manifold_average.c``
(``calculate_manifold_average``:204, ``project_procrustes[_block]``:266/346):
per direction, each frequency's solution block (viewed as a 2N x 2 complex
matrix) is defined only up to a right 2x2 unitary; averaging across
frequency first rotates every block onto a reference block (Procrustes),
then iterates {mean -> project each block onto the mean}, and finally
applies exactly ONE unitary to each original block (manifold_average.c:
147-177) so solutions are modified only by a phase/unitary factor.

TPU re-architecture: the 2x2 complex SVD-based Procrustes factor
U V^H = polar(A) is computed with a closed-form 2x2 polar decomposition
(no LAPACK), fully batched over (direction, frequency) — and on the mesh
the frequency mean is a ``psum`` (SURVEY.md P10 "manifold averaging at
iter 0").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _herm_invsqrt_2x2(H, eps=1e-12):
    """Inverse square root of a 2x2 Hermitian PSD matrix, closed form.

    sqrt(H) = (H + sqrt(det) I) / sqrt(trace + 2 sqrt(det));
    inv via adjugate. Batched over leading axes.
    """
    t = H[..., 0, 0] + H[..., 1, 1]
    d = H[..., 0, 0] * H[..., 1, 1] - H[..., 0, 1] * H[..., 1, 0]
    sd = jnp.sqrt(jnp.maximum(d.real, 0.0)).astype(H.dtype)
    denom = jnp.sqrt(jnp.maximum((t + 2 * sd).real, eps)).astype(H.dtype)
    sq = (H + sd[..., None, None] * jnp.eye(2, dtype=H.dtype)) \
        / denom[..., None, None]
    det_sq = sq[..., 0, 0] * sq[..., 1, 1] - sq[..., 0, 1] * sq[..., 1, 0]
    det_sq = jnp.where(jnp.abs(det_sq) < eps, eps, det_sq)
    adj = jnp.stack([
        jnp.stack([sq[..., 1, 1], -sq[..., 0, 1]], -1),
        jnp.stack([-sq[..., 1, 0], sq[..., 0, 0]], -1),
    ], -2)
    return adj / det_sq[..., None, None]


def polar_unitary_2x2(A):
    """U V^H of the SVD of a 2x2 complex A == its polar unitary factor:
    A (A^H A)^(-1/2). Batched."""
    AH_A = jnp.einsum("...ji,...jk->...ik", jnp.conj(A), A)
    return A @ _herm_invsqrt_2x2(AH_A)


def procrustes_project(X, Y):
    """Rotate Y onto X: Y <- Y U, U = argmin ||X - Y U||_F over unitaries.

    X, Y: [..., 2N, 2] complex stacked solution blocks
    (project_procrustes_block, manifold_average.c:346). U = polar(Y^H X).
    """
    A = jnp.einsum("...ji,...jk->...ik", jnp.conj(Y), X)  # [..., 2, 2]
    return Y @ polar_unitary_2x2(A)


def jones_to_blocks(J):
    """[..., N, 2, 2] Jones -> [..., 2N, 2] stacked blocks X = [J_1; J_2; ...].

    With this stacking the per-frequency gauge freedom of the unpolarized
    calibration problem (J_p -> J_p U, same 2x2 unitary U for every
    station; V = J_p C J_q^H invariant when C is diagonal-dominated) is a
    RIGHT multiplication X -> X U, exactly what the Procrustes projection
    removes (the role of the reference's 2N x 2 J-format blocks,
    manifold_average.c:86-96).
    """
    return J.reshape(J.shape[:-3] + (2 * J.shape[-3], 2))


def blocks_to_jones(X):
    """Inverse of :func:`jones_to_blocks`."""
    n = X.shape[-2] // 2
    return X.reshape(X.shape[:-2] + (n, 2, 2))


def _givens_from_eigvec(Z):
    """Unit eigenvector of the 3x3 rotation objective -> Givens (c, s)
    (manifold_average.c:497-506, with the sign-flip branch)."""
    pos = Z[0] >= 0.0
    Zs = jnp.where(pos, Z, -Z)
    c = jnp.sqrt(0.5 + 0.5 * Zs[0]).astype(jnp.result_type(Z, 1j))
    s = 0.5 * (Zs[1] - 1j * Zs[2]) / c
    return c, s


def extract_phases(J, niter: int = 10):
    """Phase-only diagonal Jones by joint diagonalization
    (``extract_phases``, manifold_average.c:400): iteratively rotate all
    stations' 2x2 blocks by a common Givens unitary (one sweep targets
    element (1,2), the next (2,1)) chosen as the top eigenvector of the
    accumulated 3x3 quadratic form; finally keep only unit-modulus
    diagonal entries.

    J: [N, 2, 2] complex -> [N, 2, 2] complex (diag(e^{i th0}, e^{i th1})).
    """
    cdt = J.dtype

    def h_vec(Jc, flip: bool):
        a00, a01 = Jc[:, 0, 0], Jc[:, 0, 1]
        a10, a11 = Jc[:, 1, 0], Jc[:, 1, 1]
        if not flip:
            h = jnp.stack([a00 - a11, a01 + a10, 1j * (a10 - a01)], -1)
        else:
            h = jnp.stack([a11 - a00, a10 + a01, 1j * (a01 - a10)], -1)
        return jnp.conj(h)                    # [N, 3]

    def sweep(Jc, flip: bool):
        h = h_vec(Jc, flip)
        H = jnp.einsum("ni,nj->ij", h, jnp.conj(h)).real   # 3x3 symmetric
        _, V = jnp.linalg.eigh(H)
        c, s = _givens_from_eigvec(V[:, -1])
        # row-major G = [[c, conj(s)], [-s, conj(c)]] — the reference
        # stores the same matrix column-major (manifold_average.c:505-509:
        # G[0]=c, G[1]=-s, G[2]=conj(s), G[3]=conj(c))
        G = jnp.stack([jnp.stack([c, jnp.conj(s)]),
                       jnp.stack([-s, jnp.conj(c)])]).astype(cdt)
        return jnp.einsum("nij,kj->nik", Jc, jnp.conj(G))  # J G^H

    def body(_, Jc):
        Jc = sweep(Jc, False)
        Jc = sweep(Jc, True)
        return Jc

    Jr = jax.lax.fori_loop(0, niter, body, J)
    d0 = Jr[:, 0, 0]
    d1 = Jr[:, 1, 1]
    d0 = d0 / jnp.maximum(jnp.abs(d0), 1e-30)
    d1 = d1 / jnp.maximum(jnp.abs(d1), 1e-30)
    zero = jnp.zeros_like(d0)
    return jnp.stack([jnp.stack([d0, zero], -1),
                      jnp.stack([zero, d1], -1)], -2)


def manifold_average(J, niter: int = 3, ref_index: int = 0):
    """Frequency-average solutions up to unitary ambiguity.

    J: [Nf, M, N, 2, 2] complex per-frequency per-direction Jones.
    Returns J with each (f, m) block replaced by the original block rotated
    by one unitary toward the cross-frequency average
    (calculate_manifold_average semantics; ``ref_index`` stands in for the
    reference's random initial block).

    Note: this host-mesh-agnostic version computes means over axis 0;
    in the distributed ADMM the same math runs with a psum.
    """
    X0 = jones_to_blocks(J)                        # [Nf, M, 2N, 2]
    nf = X0.shape[0]

    # initial alignment to the reference frequency's block
    ref = X0[ref_index]
    X = procrustes_project(ref[None], X0)

    # iterate mean -> project
    def body(X, _):
        mean = jnp.mean(X, axis=0, keepdims=True)
        return procrustes_project(mean, X), None
    X, _ = jax.lax.scan(body, X, None, length=niter)

    # final: ONE unitary applied to the original blocks, toward the mean
    mean = jnp.mean(X, axis=0, keepdims=True)
    Xout = procrustes_project(mean, X0)
    return blocks_to_jones(Xout)
