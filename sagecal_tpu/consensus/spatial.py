"""Spatial regularization of the consensus solution across directions.

Capability parity with the reference's ``-X l2,l1,order,fista_iters,
cadence`` feature (README.md:160-166):

- ``sharmonic_basis`` — complex spherical-harmonic modes Y_lm evaluated at
  per-cluster polar coordinates (``sharmonic_modes``, elementbeam.c:278;
  shared basis with the element beam);
- ``cluster_polar_coords`` — flux-weighted cluster centroids mapped to
  (r, theta) = (|lm| * pi/2, atan2(m, l)), replicated per hybrid chunk
  (sagecal_master.cpp:323-356);
- ``build_phi`` — Phi_k = I_2 (x) phi_k (2G x 2 block basis) and
  Phikk = sum_k Phi_k Phi_k^H + lambda I (sagecal_master.cpp:371-397);
- ``fista_spatialreg`` — the elastic-net proximal solve
  Zspat = argmin sum_k ||Zbar_k - Z Phi_k||^2 + lambda ||Z||^2 + mu ||Z||_1
  by FISTA (fista.c:36, Beck & Teboulle 2009), jitted with lax.fori_loop;
- ``spatial_predict`` — Zbar_k = Zspat Phi_k (master :796-798).

The TPU integration point is the replicated master side of the mesh ADMM
(consensus/admm.py): every ``cadence`` iterations Zbar/X are refreshed and
the Z update gains ``+ alpha Zbar - X`` with the federated (alpha-
augmented) polynomial inverse (master :668-673, :768-775).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp


def _assoc_legendre(l: int, m: int, x):
    """Associated Legendre P_l^m(x) for small static (l, m >= 0), by the
    standard recursion (elementbeam.c:238-268). Host-side numpy."""
    pmm = np.ones_like(x)
    if m > 0:
        somx2 = np.sqrt(np.maximum((1.0 - x) * (1.0 + x), 0.0))
        fact = 1.0
        for _ in range(m):
            pmm = pmm * (-fact) * somx2
            fact += 2.0
    if l == m:
        return pmm
    pmmp1 = x * (2.0 * m + 1.0) * pmm
    if l == m + 1:
        return pmmp1
    pll = pmmp1
    for i in range(m + 2, l + 1):
        pll = ((2.0 * i - 1.0) * x * pmmp1 - (i + m - 1.0) * pmm) / (i - m)
        pmm, pmmp1 = pmmp1, pll
    return pll


def sharmonic_basis(n0: int, theta, phi):
    """Complex spherical harmonics Y_lm(theta, phi) for l = 0..n0-1,
    m = -l..l -> [..., G] with G = n0^2 (sharmonic_modes,
    elementbeam.c:278; negative m via conjugation with (-1)^m).

    Host-side numpy: this is setup-time basis construction; complex
    arrays must not be built on (or transferred from) the TPU runtime.
    """
    theta = np.asarray(theta, np.float64)
    phi = np.asarray(phi, np.float64)
    ct = np.cos(theta)
    cols = []
    for l in range(n0):
        for m in range(-l, l + 1):
            am = abs(m)
            norm = math.sqrt((2 * l + 1) / (4 * math.pi)
                             * math.factorial(l - am)
                             / math.factorial(l + am))
            P = _assoc_legendre(l, am, ct)
            y = norm * P * np.exp(1j * am * phi)
            if m < 0:
                y = np.conj(y) * ((-1.0) ** am)
            cols.append(y)
    return np.stack(cols, axis=-1)


def cluster_polar_coords(sky) -> tuple[np.ndarray, np.ndarray]:
    """Flux-weighted centroid of each cluster in polar (r, theta),
    replicated per hybrid chunk -> [Mt] each (master :323-356)."""
    rr, tt = [], []
    P = (np.abs(sky.sI) + np.abs(sky.sQ) + np.abs(sky.sU)
         + np.abs(sky.sV)) * sky.smask
    for ci in range(sky.n_clusters):
        w = P[ci]
        sw = w.sum()
        if sw > 0:
            lmean = float((w * sky.ll[ci]).sum() / sw)
            mmean = float((w * sky.mm[ci]).sum() / sw)
        else:
            lmean = mmean = 0.0
        r = math.sqrt(lmean * lmean + mmean * mmean) * math.pi / 2
        t = math.atan2(mmean, lmean)
        for _ in range(int(sky.nchunk[ci])):
            rr.append(r)
            tt.append(t)
    return np.asarray(rr), np.asarray(tt)


def build_phi(n0: int, r, theta, sh_lambda: float):
    """Per-cluster basis blocks Phi [Mt, 2G, 2] = I_2 (x) phi_k and
    Phikk = sum_k Phi_k Phi_k^H + lambda I (master :371-397)."""
    phi = sharmonic_basis(n0, r, theta)                    # [Mt, G]
    Mt, G = phi.shape
    Phi = np.zeros((Mt, 2 * G, 2), complex)
    Phi[:, :G, 0] = phi
    Phi[:, G:, 1] = phi
    Phikk = np.einsum("kgi,khi->gh", Phi, Phi.conj())
    Phikk = Phikk + sh_lambda * np.eye(2 * G)
    return Phi, Phikk


def fista_spatialreg(Zbar, Phikk, Phi, mu: float, maxiter: int):
    """FISTA elastic-net solve for the spatial coefficient matrix.

    Zbar: [Mt, D, 2] complex (D = 2*Npoly*N rows per block);
    Phikk: [2G, 2G]; Phi: [Mt, 2G, 2]. Returns Zspat [D, 2G]
    (fista.c:36 ``update_spatialreg_fista``; L = ||Phikk||_F^2,
    soft-threshold applied to real and imaginary parts separately).

    Deliberate deviation from fista.c:78 (``thresh = t*mu``): the prox
    threshold there grows with the momentum parameter t, which for any
    realistic mu drives the whole solution to exactly zero within a few
    iterations. The correct ISTA prox scaling for a 1/L gradient step is
    ``mu / L`` (Beck & Teboulle 2009, eq. 1.5), used here.
    """
    D = Zbar.shape[1]
    G2 = Phikk.shape[0]
    L = jnp.sum(jnp.abs(Phikk) ** 2).real
    # sum_k Zbar_k Phi_k^H : [D, 2G]
    rhs = jnp.einsum("kdi,kgi->dg", Zbar, jnp.conj(Phi))

    def soft(Y, thr):
        def s(x):
            return jnp.sign(x) * jnp.maximum(jnp.abs(x) - thr, 0.0)
        return jax.lax.complex(s(Y.real), s(Y.imag))

    def body(it, carry):
        Z, Y, t = carry
        grad = Y @ Phikk - rhs
        Yn = Y - grad / L
        Zn = soft(Yn, mu / L)
        tn = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        sc = (tn - 1.0) / t
        Yn = (1.0 + sc) * Zn - sc * Z
        return Zn, Yn, tn

    Z0 = jnp.zeros((D, G2), Zbar.dtype)
    Z, _, _ = jax.lax.fori_loop(0, maxiter, body, (Z0, Z0, jnp.asarray(1.0)))
    return Z


def spatial_predict(Zspat, Phi):
    """Zbar_k = Zspat Phi_k -> [Mt, D, 2] (master :796-798)."""
    return jnp.einsum("dg,kgi->kdi", Zspat, Phi)


def z_r8_to_blocks(Z_r8):
    """Consensus Z [M, P, K, N, 8] reals -> [M*K, 2PN, 2] complex blocks
    (the reference's 2*Npoly*N x 2 per-effective-cluster layout). Any
    consistent row bijection works as long as :func:`blocks_to_z_r8`
    inverts it; Phi acts on the right."""
    from sagecal_tpu.consensus import manifold as mf
    from sagecal_tpu.solvers import normal_eq as ne
    J = ne.jones_r2c(Z_r8)                 # [M, P, K, N, 2, 2]
    M, P, K, N = J.shape[:4]
    J = jnp.swapaxes(J, 1, 2)              # [M, K, P, N, 2, 2]
    return mf.jones_to_blocks(J.reshape(M * K, P * N, 2, 2))


def blocks_to_z_r8(X, M: int, P: int, K: int, N: int):
    """Inverse of :func:`z_r8_to_blocks`."""
    from sagecal_tpu.consensus import manifold as mf
    from sagecal_tpu.solvers import normal_eq as ne
    J = mf.blocks_to_jones(X)              # [M*K, P*N, 2, 2]
    J = J.reshape(M, K, P, N, 2, 2)
    return ne.jones_c2r(jnp.swapaxes(J, 1, 2))


def phi_padded(sky_cmask, rr, tt, n0: int, sh_lambda: float):
    """Phi/Phikk on the padded (m, k) chunk grid: live chunk slots get
    their effective-cluster centroid basis rows, padded slots zero
    blocks. Phikk is recomputed AFTER masking — a padded slot's basis
    row evaluated at (r=0, theta=0) is nonzero for every m=0 mode and
    would otherwise add spurious Phi_k Phi_k^H terms that inflate the
    FISTA Lipschitz constant and penalize those modes (the reference
    has no padded slots: master :371-397 builds Phi from real
    centroids only). Shared by the ADMM runner and the host-side
    spatial-model writer so both see the same basis."""
    import numpy as np
    cm_np = np.asarray(sky_cmask)
    M, K = cm_np.shape
    r_pad = np.zeros((M, K))
    t_pad = np.zeros((M, K))
    idx = 0
    for m in range(M):
        for k in range(K):
            if cm_np[m, k]:
                r_pad[m, k] = rr[idx]
                t_pad[m, k] = tt[idx]
                idx += 1
    Phi, _ = build_phi(int(n0), r_pad.ravel(), t_pad.ravel(),
                       float(sh_lambda))
    Phi = Phi * cm_np.reshape(-1)[:, None, None]
    Phikk = np.einsum("kgi,khi->gh", Phi, Phi.conj())
    Phikk = Phikk + float(sh_lambda) * np.eye(Phikk.shape[0])
    return Phi, Phikk
