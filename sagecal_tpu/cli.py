"""``sagecal-tpu`` command line: flag parity with the reference binary.

Reference: ``src/MS/main.cpp:107-257`` (ParseCmdLine). Single-letter flags
keep their reference meaning so existing invocations translate directly;
long aliases are added for readability. Dispatch mirrors main.cpp:288-299:
stochastic-consensus if -N>0 and -A>1 and -w>1; stochastic if -N>0;
otherwise full batch.
"""

from __future__ import annotations

import argparse
import sys

from sagecal_tpu.config import (BeamMode, RunConfig, SimulationMode,
                                SolverMode)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sagecal-tpu",
        description="TPU-native direction-dependent calibration "
                    "(capability parity with sagecal)")
    a = p.add_argument
    a("-d", "--ms", help="dataset (SimMS directory or MS)")
    a("-f", "--ms-list", help="file/glob listing multiple datasets")
    a("-s", "--sky-model", required=False)
    a("-c", "--cluster-file", required=False)
    a("-p", "--solutions-file", help="solutions out (or in, for -a modes)")
    a("-q", "--init-solutions", help="warm-start solutions file")
    a("-F", "--format", type=int, default=0,
      help="1: sky model has 3rd-order spectral indices")
    a("-t", "--tile-size", type=int, default=120)
    a("-e", "--max-em-iter", type=int, default=3)
    a("-g", "--max-iter", type=int, default=10,
      help="max iterations within single EM (main.cpp -g; reference "
           "default 2 — the batched solvers converge per-sweep, so 10)")
    a("-l", "--max-lbfgs", type=int, default=10,
      help="max LBFGS iterations (main.cpp -l)")
    a("-m", "--lbfgs-m", type=int, default=7,
      help="LBFGS memory size (main.cpp -m)")
    a("-n", "--n-threads", type=int, default=4)
    a("-j", "--solver-mode", type=int, default=5,
      help="0 OSLM, 1 LM, 2 RLM, 3 OSRLM, 4 RTR, 5 RRTR (default), "
           "6 NSD (reference Dirac.h:1533 SM_* numbering)")
    a("-L", "--nulow", type=float, default=2.0)
    a("-H", "--nuhigh", type=float, default=30.0)
    a("--linsolv", type=int, default=1,
      help="0 Cholesky 1 QR 2 SVD (no reference letter; Data::linsolv)")
    a("-R", "--randomize", type=int, default=1)
    a("-x", "--uvmin", type=float, default=0.0,
      help="exclude baselines shorter than this (lambda; main.cpp -x)")
    a("-y", "--uvmax", type=float, default=1e9,
      help="exclude baselines longer than this (lambda; main.cpp -y)")
    a("-I", "--input-column", default="DATA",
      help="data column to calibrate (Data::DataField)")
    a("-O", "--output-column", default="CORRECTED_DATA",
      help="column receiving residuals/sim output (Data::OutField)")
    a("-o", "--mmse-rho", type=float, default=1e-9,
      help="robust rho for MMSE inversion during correction "
           "(Data::rho, residual.c)")
    a("-W", "--whiten", type=int, default=0)
    a("-D", "--diagnostics", type=int, default=0,
      help="accepted for parity; the reference's Jacobian-leverage "
           "call is disabled in v0.7.8 (fullbatch_mode.cpp:520)")
    a("--profile", default=None, metavar="DIR",
      help="write a jax.profiler trace of the first solve interval")
    a("--diag", default=None, metavar="PATH",
      help="write a JSONL diagnostic trace (phase timers + per-iteration "
           "convergence records, sagecal_tpu.diag.trace) to PATH")
    a("--metrics", default=None, metavar="PATH",
      help="enable the obs metrics registry for this run and dump it "
           "as JSON to PATH at exit (counters, gauges, latency "
           "histograms with p50/p90/p99 — sagecal_tpu.obs.metrics; "
           "off = zero overhead, bit-identical)")
    a("--tile-batch", type=int, default=1,
      help=">1: solve this many intervals as one batched device program "
           "(throughput lever; warm start becomes batch-granular)")
    a("--solve-fuse", choices=("auto", "on", "off"), default="auto",
      help="EM-sweep fusion: learn from timed sweeps (auto) or force")
    a("--solve-promote", choices=("auto", "on", "off"), default="auto",
      help="full-trace solve promotion: learn (auto) or force")
    a("--inflight", type=int, default=1,
      help="clusters solved concurrently per SAGE sweep step (block-"
           "Jacobi groups); 1 = reference Gauss-Seidel sequencing")
    a("--tile-bucket", type=int, default=0, metavar="T",
      help="pad each solve interval to T timeslots with zero-weight "
           "rows so bucket-compatible jobs share compiled programs "
           "(sagecal_tpu.serve compile cache; 0 = exact shapes, "
           "-1 = next power of two; outputs are bit-identical to any "
           "solo run at the SAME bucket)")
    a("--resume", action="store_true",
      help="re-enter a killed/failed run from its tile-boundary "
           "checkpoint (the <solutions>.ckpt.npz sidecar next to -p): "
           "completed tiles are skipped and the final residuals + "
           "solutions are bit-identical to an uninterrupted run "
           "(sequential fullbatch driver; MIGRATION.md 'Fault "
           "tolerance'). No checkpoint = start fresh")
    a("--faults", default=None, metavar="SPEC",
      help="deterministic fault-injection plan (sagecal_tpu.faults): "
           "a JSON list of rules, {'seed':..,'rules':[..]}, or a path/"
           "@path to a file holding either — chaos testing only; "
           "absent = zero cost, bit-identical")
    a("--prefetch", type=int, default=1, metavar="N",
      help="overlapped execution depth (sagecal_tpu.sched): read + "
           "host-prepare tile t+N on a background thread while tile t "
           "solves, residual/solution writes on an ordered writer "
           "thread (bit-identical outputs; default 1 = double-"
           "buffered). 0 = fully synchronous reference loop — the "
           "debugging escape hatch")
    a("--prior-cache", choices=("off", "read", "readwrite"),
      default="off",
      help="warm-start solution prior store (serve/priors.py): read = "
           "seed J0 from a banked same-key solution (sky/cluster "
           "content + station set + band + solver family), readwrite "
           "= also bank this run's final chain. Changes iteration "
           "counts, never the convergence target; off (default) is "
           "bit- and compile-count-identical to pre-prior behavior")
    a("--dtype-policy", choices=("f32", "bf16", "f16"), default="f32",
      help="storage dtype for the [B]-data (visibilities, weights, "
           "staged residual tiles, Wirtinger factors) with f32 "
           "accumulation everywhere; f32 = bit-frozen default "
           "(MIGRATION.md 'Dtype policy' for the per-policy tolerance "
           "envelopes)")
    a("--inner", choices=("chol", "cg"), default="chol",
      help="inner linear solver for the damped Gauss-Newton step: "
           "chol = dense [K,8N,8N] assembly + batched Cholesky "
           "(bit-reference); cg = matrix-free preconditioned CG "
           "(never forms the normal matrix; MIGRATION.md 'Inner "
           "linear solver')")
    a("--kernel", choices=("xla", "pallas"), default="xla",
      help="row-pass kernel for the per-cluster solve assembly: xla = "
           "bit-frozen default; pallas = fused-sweep kernel (one "
           "streaming [B]-pass per damping/TR iteration + B-"
           "independent blocks matvec per cg trip; interpret-mode on "
           "CPU; MIGRATION.md 'Pallas kernels')")
    a("--jones", choices=("full", "diag", "phase"), default="full",
      help="Jones parameterization for the solve: full = 2x2 complex "
           "per station (bit-frozen default); diag = diagonal-only "
           "(4 real params/station, 4x4 Gram blocks); phase = "
           "phase-only per polarization (2 real params/station, 2x2 "
           "Gram blocks, retraction J*exp(i*theta)). Distinct from "
           "-J/--phase-only, which phase-projects the CORRECTION "
           "after a full solve (MIGRATION.md 'Jones modes')")
    a("--shard-baselines", action="store_true",
      help="shard the baseline row axis of the (single) subband over "
           "all devices (P1 intra-subband parallelism)")
    # platform overrides (the JAX_PLATFORMS env var is ignored by some
    # TPU plugins; the config-update route always works)
    a("--platform", default=None,
      help="force the jax platform, e.g. 'cpu' for a virtual host mesh")
    a("--cpu-devices", type=int, default=0,
      help="virtual CPU device count (with --platform cpu)")
    a("-w", "--nsolbw", type=int, default=1,
      help="frequency mini-bands for bandpass consensus")
    a("-b", "--per-channel", type=int, default=0)
    a("-a", "--simulation", type=int, default=0,
      help="1 simulate, 2 add model, 3 subtract model")
    a("-z", "--ignore-clusters", help="file of cluster ids to ignore")
    a("-k", "--correct-cluster", type=int, default=None,
      help="cluster id whose solutions correct the residual")
    a("-J", "--phase-only", type=int, default=0,
      help=">0: phase-only correction (joint-diagonalized phases)")
    a("-B", "--beam", type=int, default=0)
    a("-N", "--epochs", type=int, default=0,
      help=">0 enables stochastic (minibatch) calibration")
    a("--loss", choices=("robust", "huber"), default="robust",
      help="stochastic minibatch loss (Student's t or Huber)")
    a("-M", "--minibatches", type=int, default=1)
    a("-A", "--admm", type=int, default=1)
    a("-P", "--npoly", type=int, default=2)
    a("-Q", "--polytype", type=int, default=2)
    a("-r", "--rho", type=float, default=5.0)
    a("-G", "--rho-file", default=None)
    a("-T", "--max-timeslots", type=int, default=0)
    a("-V", "--verbose", action="store_true")
    return p


def warn_legacy_flags(args, err=sys.stderr) -> list:
    """One-time startup warning for short-option values that suggest a
    pre-remap command line. The reference-parity remap is silent by
    design (same letters, same meanings), which also means a command
    line written for a DIFFERENT tool or an old habit fails silently:
    a ``-y`` under 10 lambda excludes essentially every baseline, and
    an ``-o`` (MMSE rho) above 1 is far outside the regularization
    regime (reference default 1e-9) — both almost certainly meant
    something else. The run proceeds; the warning names the flag."""
    warnings = []
    if args.uvmax < 10.0:
        warnings.append(
            f"-y/--uvmax={args.uvmax:g} lambda excludes nearly all "
            "baselines; the reference -y is an upper uv-distance cut in "
            "lambda (default 1e9) — was this meant for another tool?")
    if args.mmse_rho > 1.0:
        warnings.append(
            f"-o/--mmse-rho={args.mmse_rho:g} is far above the MMSE "
            "regularization regime (reference default 1e-9); the "
            "reference -o is the robust rho for residual correction — "
            "not an output path or a solver knob")
    for w in warnings:
        print(f"WARNING: suspicious legacy option value: {w}", file=err)
    return warnings


def config_from_args(args) -> RunConfig:
    return RunConfig(
        ms=args.ms, ms_list=args.ms_list, sky_model=args.sky_model,
        cluster_file=args.cluster_file, solutions_file=args.solutions_file,
        init_solutions=args.init_solutions, format_3=bool(args.format),
        tile_size=args.tile_size, max_em_iter=args.max_em_iter,
        max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        input_column=args.input_column, output_column=args.output_column,
        mmse_rho=args.mmse_rho,
        n_threads=args.n_threads, solver_mode=SolverMode(args.solver_mode),
        robust_nulow=args.nulow, robust_nuhigh=args.nuhigh,
        linsolv=args.linsolv, randomize=bool(args.randomize),
        uvmin=args.uvmin, uvmax=args.uvmax, whiten=bool(args.whiten),
        channel_avg_per_band=args.nsolbw,
        per_channel_bfgs=bool(args.per_channel),
        simulation=SimulationMode(args.simulation),
        ignore_clusters_file=args.ignore_clusters,
        correct_cluster=args.correct_cluster,
        phase_only=bool(args.phase_only), beam_mode=BeamMode(args.beam),
        n_epochs=args.epochs, n_minibatches=args.minibatches,
        stochastic_loss=args.loss,
        n_admm=args.admm, n_poly=args.npoly, poly_type=args.polytype,
        admm_rho=args.rho, rho_file=args.rho_file,
        max_timeslots=args.max_timeslots, verbose=args.verbose,
        profile_dir=args.profile,
        tile_batch=args.tile_batch, solve_fuse=args.solve_fuse,
        solve_promote=args.solve_promote,
        cluster_inflight=args.inflight,
        solver_inner=args.inner,
        solver_kernel=args.kernel,
        jones_mode=args.jones,
        dtype_policy=args.dtype_policy,
        tile_bucket=args.tile_bucket,
        prefetch=args.prefetch,
        prior_cache=args.prior_cache,
        resume=bool(args.resume),
        shard_baselines=bool(args.shard_baselines))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform or args.cpu_devices:
        import jax
        if args.platform:
            jax.config.update("jax_platforms", args.platform)
        if args.cpu_devices:
            from sagecal_tpu.compat import set_cpu_device_count
            set_cpu_device_count(args.cpu_devices)
    cfg = config_from_args(args)
    if (not cfg.ms and not cfg.ms_list) or not cfg.sky_model \
            or not cfg.cluster_file:
        print("need -d dataset (or -f list), -s sky model, -c cluster file",
              file=sys.stderr)
        return 2
    warn_legacy_flags(args)

    if args.diag:
        from sagecal_tpu.diag import trace as dtrace
        dtrace.enable(args.diag, entry="sagecal-tpu",
                      argv=list(argv) if argv is not None else sys.argv[1:])
    if args.metrics:
        from sagecal_tpu.obs import metrics as ometrics
        ometrics.enable()
    if args.faults:
        from sagecal_tpu import faults
        faults.enable_spec(args.faults)

    from sagecal_tpu import pipeline
    try:
        if cfg.n_epochs > 0:
            from sagecal_tpu import stochastic
            if cfg.n_admm > 1 and cfg.channel_avg_per_band > 1:
                stochastic.run_minibatch_consensus(cfg)
            else:
                stochastic.run_minibatch(cfg)
        else:
            pipeline.run(cfg)
    finally:
        if args.diag:
            dtrace.disable()
        if args.metrics:
            ometrics.dump_to(args.metrics)
    return 0


if __name__ == "__main__":
    sys.exit(main())
