"""Overlapped host execution: prefetch, ordered async writeback, rings.

The calibration host loops (pipeline.py, stochastic.py, cli_mpi.py)
execute io -> stage -> solve -> residual-fetch -> write per solve
interval. PR 1's roofline measured the solve as bandwidth-bound, so
the device idles through every host-side phase of that chain. This
module holds the three primitives that hide those phases behind the
solve without changing a single computed bit:

- :class:`Prefetcher` — a bounded-depth background producer: tile t+1
  is read (and host-prepared, when the caller's ``produce`` stages too)
  on a reader thread while tile t solves. The consumer observes only
  its *wait* for each item — the pipeline bubble — which is what the
  diag "io" phase must record under overlap (the thread's own
  production time is emitted separately, tagged ``bg``).
- :class:`AsyncWriter` — one writer thread executing submitted jobs
  strictly in submission order (MS residual tiles, solution rows). An
  exception in any job fails the run at the next tile boundary with
  the original traceback — never swallowed; ``--prefetch 0`` is the
  debugging escape hatch that runs every job inline.
- :class:`DonatedRing` — an N-slot ring for staged device buffers
  whose consumer DONATES them (the per-tile residual input, PR 2's
  contract). Under overlap the next tile's buffer is staged while the
  previous one is still in flight; the ring guarantees a donated slot
  is never read again and a live slot is never overwritten.

Ordering guarantees (the embedder contract, MIGRATION.md "Overlapped
execution"): items are produced and consumed strictly in index order;
write jobs execute strictly in submission order; the warm-start solve
chain stays sequential — only data movement overlaps. Memory cost is
bounded: ``depth`` extra staged tiles plus the writer queue.

Fault tolerance (MIGRATION.md "Fault tolerance"): producer calls and
writer jobs run under ``faults.retry_transient`` — a transient
read/write failure retries with bounded exponential backoff before
the fail-stop paths above fire with the original traceback — and the
``reader_thread``/``writer_thread`` injection points let the chaos
harness kill either thread deterministically. Expired thread joins at
close() are LOUD (stderr warning + ``thread_join_timeouts_total``).

Layering: stdlib + faults + diag.trace only. Device arrays pass
through opaquely; the non-blocking device->host copy
(``copy_to_host_async``) is started by callers before submitting a
fetch job here.
"""

from __future__ import annotations

import queue
import sys
import threading
import time

from sagecal_tpu import faults
from sagecal_tpu.analysis import threadsan
from sagecal_tpu.diag import trace as dtrace
from sagecal_tpu.obs import metrics as obs


def _warn_join_timeout(role: str, name: str, timeout_s: float) -> None:
    """A ``join(timeout=...)`` that expired used to abandon the hung
    thread SILENTLY — the leak was invisible until the process ran out
    of threads. Now it is loud (stderr) and counted
    (``thread_join_timeouts_total{role=}``) so leaked threads show up
    in /metrics (MIGRATION.md "Fault tolerance")."""
    obs.inc("thread_join_timeouts_total", role=role)
    print(f"WARNING: {role} thread {name!r} did not exit within "
          f"{timeout_s:.0f}s; abandoning it (leak counted in "
          f"thread_join_timeouts_total)", file=sys.stderr)


def start_host_copy(*arrays) -> None:
    """Start the non-blocking device->host copy of jax arrays (the
    blessed async-readback API — see analysis/hostsync.py): the DMA
    overlaps with subsequent dispatches, so the writer thread's later
    ``np.asarray`` finds the bytes already on host. A backend without
    the method just pays the copy at fetch time."""
    for a in arrays:
        fn = getattr(a, "copy_to_host_async", None)
        if fn is not None:
            fn()


class EndOfStream(Exception):
    """Raised by an open-ended producer (``n=None``) — by ``fn`` or by
    the ``arrive`` hook — to signal clean end of input. NOT an error:
    the Prefetcher converts it into normal iterator/poll() completion,
    exactly as if a known ``n`` had been reached."""


class Prefetcher:
    """Produce ``fn(i)`` for ``i in range(n)`` ``depth`` items ahead.

    Iterating yields ``(i, item, wait_s)`` in index order; ``wait_s``
    is the host time spent BLOCKED on the item, EXCLUDING any
    arrival/pacing wait (attributed separately — see below).
    ``depth <= 0`` runs ``fn`` inline (the synchronous reference path)
    and ``wait_s`` is then the full production time. Producer
    exceptions re-raise in the consumer with the original traceback;
    abandoning the iterator (``close()``/GC) cancels the thread.

    ``n=None`` runs OPEN-ENDED: items are produced for i = 0, 1, ...
    until ``fn`` (or the ``arrive`` hook) raises :class:`EndOfStream`
    — the live-ingest regime where the tile count is not known at
    start (sagecal_tpu.stream).

    Arrival attribution (diag phase ``arrival_wait``): time spent
    waiting for an item to BECOME AVAILABLE — the ``pace_s`` ingest
    clock or the ``arrive`` hook's block-until-arrival — is its own
    phase, never folded into the ``read`` production phase or the
    consumer's io wait. The producer side emits it ``bg``-tagged; the
    consumer side emits the portion of its own block that overlapped
    the wait-for-arrival (so the io bubble stays an honest measure of
    read/stage cost, not of the tenant's data rate).
    """

    #: poll() sentinels (serve scheduler protocol)
    EMPTY = object()    # production still in flight — try again later
    DONE = object()     # all n items consumed

    def __init__(self, fn, n: int | None, depth: int = 1,
                 name: str = "read", context=None, ready_event=None,
                 join_timeout_s: float = 5.0, pace_s: float = 0.0,
                 arrive=None):
        self.fn = fn
        self.n = None if n is None else int(n)
        self.depth = int(depth)
        self.name = name
        self.join_timeout_s = float(join_timeout_s)
        # streaming-ingest model (--tile-arrival): item i becomes
        # producible no earlier than start + i * pace_s, as if tiles
        # arrived from a rate-limited tenant stream (the LOFAR/SKA
        # quasi-real-time regime, arXiv:1410.2101). Pure wait — the
        # produced bytes, and therefore every output, are unchanged.
        self.pace_s = max(0.0, float(pace_s))
        # true-streaming arrival hook (sagecal_tpu.stream): a callable
        # ``arrive(cancel_event) -> t_arrival`` that blocks until the
        # NEXT item is available and returns its arrival timestamp
        # (time.monotonic domain), or raises EndOfStream. Supersedes
        # pace_s when set. Must honor the cancel event so close()
        # stays prompt.
        self._arrive = arrive
        self._t0 = time.monotonic()
        # zero-arg context-manager factory entered for the producer
        # thread's lifetime (serve: routes the thread's diag emits to
        # the owning job's tracer via dtrace.scope)
        self._ctx = context
        # optional shared Event set after every successful production:
        # a poll()-driven consumer (the serve device-owner loop) waits
        # on it instead of sleeping a fixed quantum, so a staged tile
        # wakes the device immediately — the poll-path equivalent of
        # the iterator's blocking get()
        self._ready = ready_event
        self._cancel = threading.Event()
        self._q: queue.Queue = queue.Queue(maxsize=max(self.depth, 1))
        self._thread = None
        self._poll_next = 0       # inline (depth<=0) poll cursor
        self._poll_done = False
        if self.depth > 0:
            self._thread = threading.Thread(
                target=self._producer, name=f"prefetch-{name}",
                daemon=True)
            self._thread.start()

    # -- producer thread ---------------------------------------------------

    def _wait_arrival(self, i):
        """Block until item ``i`` is AVAILABLE (the pace_s ingest
        clock, or the ``arrive`` transport hook). Returns
        ``(waited_s, t_arrival)`` with ``t_arrival`` in the
        time.monotonic domain; raises :class:`EndOfStream` when the
        arrive hook reports end of input. This wait is attributed as
        the ``arrival_wait`` phase by the caller — NEVER as read/io
        time: it measures the tenant's data rate, not our cost."""
        if self._arrive is not None:
            t0 = time.monotonic()
            t_arr = self._arrive(self._cancel)
            return time.monotonic() - t0, t_arr
        if self.pace_s > 0.0:
            # ingest pacing: wait out the synthetic arrival time (the
            # cancel event bounds the wait so close() stays prompt)
            t0 = time.monotonic()
            due = self._t0 + i * self.pace_s
            while not self._cancel.is_set():
                delay = due - time.monotonic()
                if delay <= 0:
                    break
                self._cancel.wait(min(delay, 0.2))
            now = time.monotonic()
            return now - t0, max(due, t0)
        return 0.0, time.monotonic()

    def _emit_arrival(self, i, waited, bg, observe=True):
        """The ``arrival_wait`` diag phase (+ metric). The consumer
        side passes ``observe=False`` — its overlap with the producer's
        wait is the SAME wall time, and the metric must count each
        waited second once."""
        if waited > 0.0:
            dtrace.emit("phase", name="arrival_wait", tile=i,
                        dur_s=waited, bg=bg)
            if observe:
                obs.observe("tile_arrival_wait_seconds", waited)

    def _call(self, i):
        """One production, with the fault-tolerance layer around it:
        the ``reader_thread`` injection point (thread-death chaos
        lever), then bounded transient retry — a flaky read/stage
        recovers here with backoff instead of killing the run; a
        non-transient or budget-exhausted failure re-raises with its
        original traceback into the existing propagation path.
        Retrying the whole ``fn(i)`` is safe by the staging contract:
        reads are pure and a producer's only durable side effect
        (``DonatedRing.stage``) is its final statement."""
        faults.inject("reader_thread", key=i)
        return faults.retry_transient(self.fn, (i,), what="read", key=i)

    def _put(self, item) -> bool:
        while not self._cancel.is_set():
            try:
                self._q.put(item, timeout=0.2)
                if self._ready is not None:
                    self._ready.set()
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        if self._ctx is not None:
            with self._ctx():
                return self._produce_loop()
        return self._produce_loop()

    def _produce_loop(self):
        try:
            i = 0
            while self.n is None or i < self.n:
                if self._cancel.is_set():
                    return
                try:
                    waited, t_arr = self._wait_arrival(i)
                except EndOfStream:
                    break
                if self._cancel.is_set():
                    return
                self._emit_arrival(i, waited, bg=True)
                t0 = time.perf_counter()
                try:
                    item = self._call(i)
                except EndOfStream:
                    break
                # the background production time — NOT the consumer's
                # io wait, and NOT the arrival wait (emitted above);
                # tagged bg so attribution stays honest
                dur = time.perf_counter() - t0
                dtrace.emit("phase", name=self.name, tile=i,
                            dur_s=dur, bg=True)
                obs.observe("prefetch_read_seconds", dur)
                if not self._put((i, item, t_arr)):
                    return
                i += 1
        except BaseException as e:      # surface in the consumer
            self._put((None, e, 0.0))
            return
        self._put((None, None, 0.0))

    # -- consumer ----------------------------------------------------------

    def __iter__(self):
        if self.depth <= 0:
            i = 0
            while self.n is None or i < self.n:
                try:
                    waited, _t_arr = self._wait_arrival(i)
                except EndOfStream:
                    return
                self._emit_arrival(i, waited, bg=False)
                t0 = time.perf_counter()
                try:
                    item = self._call(i)
                except EndOfStream:
                    return
                yield i, item, time.perf_counter() - t0
                i += 1
            return
        try:
            while True:
                t0 = time.monotonic()
                i, item, t_arr = self._q.get()
                t1 = time.monotonic()
                wait = t1 - t0
                if i is None:
                    if item is not None:
                        raise item
                    return
                # split the block: the part spent while the item had
                # not yet ARRIVED is arrival wait (the tenant's data
                # rate), only the remainder is the io bubble (our
                # read/stage cost)
                arr = min(max(t_arr - t0, 0.0), wait)
                self._emit_arrival(i, arr, bg=False, observe=False)
                yield i, item, wait - arr
        finally:
            self.close()

    def poll(self):
        """Non-blocking consumption for the serve scheduler's
        device-owner loop: returns ``(i, item, wait_s)`` when the next
        item is ready, :attr:`EMPTY` while production is still in
        flight (the scheduler moves on to another job's ready tile
        instead of blocking the device here), or :attr:`DONE` after
        item ``n - 1``. Producer exceptions re-raise at the poll that
        would have returned their item. ``depth <= 0`` produces inline
        (always "ready"; ``wait_s`` is then the production time).
        Items arrive strictly in index order, same as iteration — a
        consumer uses EITHER the iterator OR poll(), never both."""
        if self._poll_done:
            return self.DONE
        if self.depth <= 0:
            if self.n is not None and self._poll_next >= self.n:
                self._poll_done = True
                return self.DONE
            i = self._poll_next
            try:
                waited, _t_arr = self._wait_arrival(i)
                self._emit_arrival(i, waited, bg=False)
                t0 = time.perf_counter()
                item = self._call(i)
            except EndOfStream:
                self._poll_done = True
                return self.DONE
            self._poll_next += 1
            return i, item, time.perf_counter() - t0
        try:
            i, item, _t_arr = self._q.get_nowait()
        except queue.Empty:
            return self.EMPTY
        if i is None:
            self._poll_done = True
            if item is not None:
                raise item
            return self.DONE
        return i, item, 0.0

    def close(self):
        self._cancel.set()
        while True:                     # unblock a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread is not None:
            self._thread.join(timeout=self.join_timeout_s)
            if self._thread.is_alive():
                _warn_join_timeout("reader", f"prefetch-{self.name}",
                                   self.join_timeout_s)
            self._thread = None


class AsyncWriter:
    """Strictly ordered background execution of write jobs.

    ``submit(fn, *args)`` enqueues; one writer thread runs jobs in
    submission order. After a job raises, no later job executes: the
    exception re-raises (original traceback) at the caller's next
    :meth:`check` — pipelines call it at every tile boundary — or at
    :meth:`close`. ``enabled=False`` degrades to inline execution
    (identical semantics, zero threads): the ``--prefetch 0`` path.

    ``submit`` returns the seconds it spent blocked on a full queue
    (writer backpressure — bubble time for the caller's accounting).
    """

    _STOP = object()

    def __init__(self, enabled: bool = True, maxsize: int = 4,
                 context=None, join_timeout_s: float = 10.0):
        self.enabled = bool(enabled)
        self.join_timeout_s = float(join_timeout_s)
        # zero-arg context-manager factory entered for the writer
        # thread's lifetime (serve: per-job diag scope, as Prefetcher)
        self._ctx = context
        # _exc has TWO writers — the writer thread (job failure) and
        # the closing caller (flush timeout) — and first-failure-wins
        # semantics; the lock makes that race a rule instead of luck
        # (threadlint shared-state; instrumented under
        # --sanitize-threads)
        self._exc_lock = threadsan.make_lock("AsyncWriter._exc")
        self._exc = None
        self._raised = False
        self._q: queue.Queue = queue.Queue(maxsize=max(maxsize, 1))
        self._thread = None
        if self.enabled:
            self._thread = threading.Thread(
                target=self._worker, name="async-writer", daemon=True)
            self._thread.start()

    def _worker(self):
        if self._ctx is not None:
            with self._ctx():
                return self._work_loop()
        return self._work_loop()

    def _work_loop(self):
        while True:
            job = self._q.get()
            try:
                if job is self._STOP:
                    return
                with self._exc_lock:
                    failed = self._exc is not None
                if not failed:          # fail-stop: drain, don't run
                    fn, args, kwargs = job
                    # writer_thread: the thread-death injection point;
                    # then bounded transient retry — submitted jobs are
                    # idempotent (atomic MS tile writes, single-call
                    # solution/checkpoint writes), so a flaky disk
                    # recovers here instead of failing the run
                    faults.inject("writer_thread")
                    faults.retry_transient(fn, args, kwargs,
                                           what="write")
            except BaseException as e:
                with self._exc_lock:
                    if self._exc is None:   # first failure wins
                        self._exc = e
            finally:
                self._q.task_done()

    def check(self) -> None:
        """Re-raise a pending writer failure (original traceback).
        Raises once: after it fired, the run is already unwinding and
        the cleanup-path re-check must not mask the original."""
        with self._exc_lock:
            exc = self._exc
        if exc is not None and not self._raised:
            self._raised = True
            raise exc

    def submit(self, fn, *args, **kwargs) -> float:
        self.check()
        if not self.enabled:
            # inline (--prefetch 0) execution keeps the SAME transient
            # retry as the writer thread; a non-transient failure
            # raises here at the call site (the debugging contract)
            faults.retry_transient(fn, args, kwargs, what="write")
            return 0.0
        t0 = time.perf_counter()
        self._q.put((fn, args, kwargs))
        wait = time.perf_counter() - t0
        if wait > 1e-3:
            # writer backpressure: the producer outran the disk and
            # blocked on a full queue — bubble time for the caller and
            # an SLO signal for the serve daemon. The 1 ms floor keeps
            # the lock-free fast path (sub-µs put) out of the counter.
            obs.inc("writer_backpressure_seconds_total", wait)
        return wait

    def drain(self) -> float:
        """Block until every submitted job ran; returns the wait."""
        t0 = time.perf_counter()
        if self.enabled:
            self._q.join()
        self.check()
        return time.perf_counter() - t0

    def _join_queue(self, timeout_s: float) -> bool:
        """``Queue.join`` with a deadline (the stdlib one has none): a
        writer job hung on dead storage must not hang ``close`` — and
        the whole run's teardown — forever. Uses the queue's own
        ``all_tasks_done`` condition, the documented synchronization
        primitive behind ``join``."""
        deadline = time.perf_counter() + timeout_s
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return False
                self._q.all_tasks_done.wait(remaining)
        return True

    def close(self, raise_pending: bool = True) -> None:
        if self._thread is not None:
            flushed = self._join_queue(self.join_timeout_s)
            if flushed:
                self._q.put(self._STOP)
                self._thread.join(timeout=self.join_timeout_s)
            if not flushed or self._thread.is_alive():
                _warn_join_timeout("writer", "async-writer",
                                   self.join_timeout_s)
                with self._exc_lock:
                    if self._exc is None:
                        # an abandoned flush means submitted writes
                        # may never have landed: that is a FAILURE the
                        # raise_pending path must surface — a run
                        # whose last writes hang must not report
                        # success (and must not delete its resume
                        # checkpoint). The hung writer may still fail
                        # later; whichever lands first under the lock
                        # wins, neither is silently lost
                        self._exc = TimeoutError(
                            "async-writer failed to flush within "
                            f"{self.join_timeout_s:.0f}s; submitted "
                            "writes may not have landed")
            self._thread = None
        if raise_pending:
            self.check()


class DonatedRing:
    """N-slot ring of staged device buffers consumed by DONATION.

    The per-tile residual program donates its staged visibility input
    (PR 2's buffer-donation contract). Under overlap the producer
    stages tile t+1's buffer while tile t's is still in flight, so the
    donated buffer must alternate slots instead of aliasing in-flight
    memory. The ring enforces the two safety rules statically checked
    nowhere else:

    - :meth:`take` hands the buffer out exactly once (the donating
      call); a second read of the slot RAISES instead of touching
      memory XLA may already have reclaimed;
    - :meth:`stage` refuses to overwrite a slot whose buffer was never
      consumed (an in-flight donation would alias).

    Slot choice is ``tag % depth``; sizing is the caller's prefetch
    depth + 1 (two slots for the default double-buffered loop).
    """

    def __init__(self, depth: int = 2):
        self.depth = max(int(depth), 1)
        self._bufs = [None] * self.depth
        self._live = [False] * self.depth
        self._tags = [None] * self.depth
        self._lock = threadsan.make_lock("DonatedRing._lock")

    # thread-role: prefetch, caller
    def stage(self, tag: int, buf) -> None:
        with self._lock:
            threadsan.guard(self._lock, "DonatedRing slots")
            i = tag % self.depth
            if self._live[i]:
                raise RuntimeError(
                    f"DonatedRing: staging tag {tag} would overwrite "
                    f"slot {i} (tag {self._tags[i]}) whose buffer was "
                    f"never taken — in-flight donation would alias")
            self._bufs[i] = buf
            self._live[i] = True
            self._tags[i] = tag

    def take(self, tag: int):
        """The buffer for ``tag``, exactly once (caller donates it)."""
        with self._lock:
            threadsan.guard(self._lock, "DonatedRing slots")
            i = tag % self.depth
            if not self._live[i] or self._tags[i] != tag:
                raise RuntimeError(
                    f"DonatedRing: tag {tag} not staged in slot {i} "
                    f"(slot holds tag {self._tags[i]}, "
                    f"live={self._live[i]}) — read after donation?")
            buf, self._bufs[i] = self._bufs[i], None
            self._live[i] = False
            return buf
