from sagecal_tpu.rime import envelopes as envelopes
from sagecal_tpu.rime import predict as predict
