"""Residual computation, subtraction and correction.

Capability parity with reference ``src/lib/Radio/residual.c``:
- ``calculate_residuals_multifreq`` (:930): per-channel model with catalog
  spectra, subtract J_p C J_q^H for subtractable clusters, optionally
  correct the residual by the inverse solution of one cluster (``-k``)
  with an MMSE-regularized 2x2 inverse (``mat_invert`` :163);
- ``predict_visibilities_multifreq[_withsol]`` (:1242/:1601): simulation
  modes (replace/add/subtract, ignore lists, optional correction).

Negative cluster ids are solved for but never subtracted (README.md:50);
that policy arrives here as ``subtract_mask``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sagecal_tpu.rime import predict as rp


def residual_writeback(res, out_dtype=None):
    """[..., 2, 2] complex residual -> stacked real pairs [..., 2] in
    the dtype-policy storage dtype.

    The writeback emission point of the residual pipeline: under a
    reduced policy the device->host readback (and the DonatedRing slot
    that carried the staged input) ships half the bytes, while the
    residual subtraction itself stays c64. ``out_dtype`` None or
    f32/f64 is the identity path (the pre-policy utils.c2r layout).
    """
    from sagecal_tpu import dtypes as dtp
    out = jnp.stack([res.real, res.imag], axis=-1)
    return out if out_dtype is None else dtp.to_storage(out, out_dtype)


def mmse_inverse(J, rho):
    """Regularized 2x2 inverse: inv(J + rho I), det nudged by rho when
    nearly singular (residual.c:163 ``mat_invert``)."""
    a = J + rho * jnp.eye(2, dtype=J.dtype)
    det = a[..., 0, 0] * a[..., 1, 1] - a[..., 0, 1] * a[..., 1, 0]
    det = jnp.where(jnp.sqrt(jnp.abs(det)) <= rho, det + rho, det)
    inv = jnp.stack([
        jnp.stack([a[..., 1, 1], -a[..., 0, 1]], -1),
        jnp.stack([-a[..., 1, 0], a[..., 0, 0]], -1),
    ], -2)
    return inv / det[..., None, None]


def correct_by_cluster(res, J_m, sta1, sta2, chunk_idx_m, rho,
                       phase_only: bool = False):
    """Apply inv(J_p) res inv(J_q)^H using cluster ``m``'s solutions
    (residual.c:945-1030 correction path). With ``phase_only`` (-J flag)
    each chunk's solutions are first reduced to unit-modulus diagonal
    phases by joint diagonalization (residual.c:965-980 +
    extract_phases). res: [B, F, 2, 2]."""
    if phase_only:
        from sagecal_tpu.consensus import manifold as mf
        J_m = jax.vmap(mf.extract_phases)(J_m)        # per chunk [K,N,2,2]
    Jinv = mmse_inverse(J_m, jnp.asarray(rho, J_m.real.dtype))  # [K,N,2,2]
    Gp = Jinv[chunk_idx_m, sta1]
    Gq = Jinv[chunk_idx_m, sta2]
    return jnp.einsum("bij,bfjk,bkl->bfil", Gp, res,
                      jnp.conj(jnp.swapaxes(Gq, -1, -2)))


def calculate_residuals_multifreq(sky: rp.SkyArrays, J, x, u, v, w, freqs,
                                  fdelta_chan, sta1, sta2, chunk_idx,
                                  subtract_mask, correct_idx: int | None = None,
                                  rho: float = 1e-9,
                                  beam=None, dobeam: int = 0, tslot=None,
                                  phase_only: bool = False):
    """Residual x - sum_m J_p C_m(f) J_q^H over subtractable clusters.

    x: [B, F, 2, 2]; J: [M, Kmax, N, 2, 2]; chunk_idx: [M, B];
    subtract_mask: [M] bool; ``correct_idx`` is the PADDED-ARRAY index of
    the cluster whose solutions correct the residual (host code resolves
    the user-facing ``-k`` cluster id to an index).

    With ``beam``/``dobeam`` this is calculate_residuals_multifreq_withbeam
    (predict_withbeam.c:1895). Returns [B, F, 2, 2] residuals.
    """
    coh = rp.coherencies(sky, u, v, w, freqs, fdelta_chan,
                         per_channel_flux=True, beam=beam, dobeam=dobeam,
                         tslot=tslot, sta1=sta1, sta2=sta2)
    model = rp.predict_model(coh, J, sta1, sta2, chunk_idx,
                             cluster_mask=subtract_mask)
    res = x - model
    if correct_idx is not None:
        res = correct_by_cluster(res, J[correct_idx], sta1, sta2,
                                 chunk_idx[correct_idx], rho,
                                 phase_only=phase_only)
    return res


def calculate_residuals_interp(sky: rp.SkyArrays, J_old, J_new, x, u, v, w,
                               freqs, fdelta_chan, sta1, sta2, chunk_idx,
                               subtract_mask, correct_idx: int | None = None,
                               rho: float = 1e-9):
    """Residuals with OLD-solution correction (``calculate_residuals_interp``,
    residual.c:201): subtract the model corrupted by the NEW solutions,
    correct the residual with the inverse of the OLD solutions' cluster
    ``correct_idx``. (The reference's time interpolation between the two
    is disabled upstream — residual.c:288 'interpolation is disabled for
    the moment' — so this matches its actual behavior.)
    """
    coh = rp.coherencies(sky, u, v, w, freqs, fdelta_chan,
                         per_channel_flux=True)
    model = rp.predict_model(coh, J_new, sta1, sta2, chunk_idx,
                             cluster_mask=subtract_mask)
    res = x - model
    if correct_idx is not None:
        res = correct_by_cluster(res, J_old[correct_idx], sta1, sta2,
                                 chunk_idx[correct_idx], rho)
    return res


def simulate_visibilities(sky: rp.SkyArrays, x, u, v, w, freqs, fdelta_chan,
                          sta1, sta2, mode: int, J=None, chunk_idx=None,
                          ignore_mask=None, correct_idx: int | None = None,
                          rho: float = 1e-9,
                          beam=None, dobeam: int = 0, tslot=None):
    """Simulation modes (-a 1/2/3): replace/add/subtract the model
    (residual.c:1242 predict_visibilities_multifreq, :1601 _withsol;
    with beam: predict_visibilities_multifreq_with[sol_with]beam_gpu
    semantics, Radio.h:400-446).

    ``J`` (optional) corrupts the model with solutions; ``ignore_mask`` [M]
    True = keep cluster in the simulated model (reference ignorelist holds
    clusters to skip).
    """
    coh = rp.coherencies(sky, u, v, w, freqs, fdelta_chan,
                         per_channel_flux=True, beam=beam, dobeam=dobeam,
                         tslot=tslot, sta1=sta1, sta2=sta2)
    M, B = coh.shape[0], coh.shape[1]
    mask = (jnp.ones((M,), bool) if ignore_mask is None
            else jnp.asarray(ignore_mask))
    if J is not None:
        if chunk_idx is None:
            chunk_idx = jnp.zeros((M, B), jnp.int32)
        model = rp.predict_model(coh, J, sta1, sta2, chunk_idx,
                                 cluster_mask=mask)
    else:
        model = jnp.sum(jnp.where(mask[:, None, None, None, None], coh, 0.0),
                        axis=0)
    if mode == 2:       # SIMUL_ADD
        out = x + model
    elif mode == 3:     # SIMUL_SUB
        out = x - model
    else:               # SIMUL_ONLY
        out = model
    if correct_idx is not None and J is not None:
        out = correct_by_cluster(out, J[correct_idx], sta1, sta2,
                                 chunk_idx[correct_idx], rho)
    return out
