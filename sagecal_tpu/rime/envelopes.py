"""Extended-source visibility envelopes, vectorized for TPU.

Capability parity with reference ``src/lib/Radio/predict.c``
(``gaussian_contrib``:193, ``ring_contrib``:222, ``disk_contrib``:237,
``shapelet_contrib``:142 with Hermite recursion ``H_e``:31) — re-designed as
masked array ops over a [..., S] source grid instead of per-source function
pointers, so one fused XLA computation evaluates every morphology.

All inputs are in wavelengths (u·f/c etc. — callers pass u_sec * freq).
Padded sources must carry eX=eY=0; every division here is guarded so padded
lanes produce finite garbage that gets masked by zero flux downstream.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from sagecal_tpu.skymodel import (
    STYPE_DISK, STYPE_GAUSSIAN, STYPE_POINT, STYPE_RING, STYPE_SHAPELET,
)


def _project_uv(u, v, w, cxi, sxi, cphi, sphi, use_projection, negate):
    """Rotate (u,v,w) into the source-local tangent frame.

    Reference predict.c:168-180 (gaussian) / :152-158 (shapelet, negated
    variant). Disk/ring always project (predict.c:224-245); gaussian and
    shapelet only when the source sits far from the phase center
    (use_projection flag, readsky.c:420-424).
    """
    up = u * cxi - v * cphi * sxi + w * sphi * sxi
    vp = u * sxi + v * cphi * cxi - w * sphi * cxi
    if negate:
        # shapelet variant negates the projected frame only (predict.c:152-158);
        # the unprojected branch stays (u, v)
        up, vp = -up, -vp
    up = jnp.where(use_projection, up, u)
    vp = jnp.where(use_projection, vp, v)
    return up, vp


def gaussian(u, v, w, eX, eY, eP, cxi, sxi, cphi, sphi, use_projection):
    """predict.c:193 — pi/2 * exp(-(ut^2+vt^2)), axes pre-doubled at parse."""
    up, vp = _project_uv(u, v, w, cxi, sxi, cphi, sphi, use_projection,
                         negate=False)
    sinph, cosph = jnp.sin(eP), jnp.cos(eP)
    ut = eX * (cosph * up - sinph * vp)
    vt = eY * (sinph * up + cosph * vp)
    return (jnp.pi / 2.0) * jnp.exp(-(ut * ut + vt * vt))


def _bessel_j0(x):
    """Abramowitz & Stegun 9.4.1/9.4.3 rational approximations (|err|<1e-7)."""
    ax = jnp.abs(x)
    # small |x|
    y = x * x
    p_small = (57568490574.0 + y * (-13362590354.0 + y * (651619640.7
               + y * (-11214424.18 + y * (77392.33017 + y * (-184.9052456))))))
    q_small = (57568490411.0 + y * (1029532985.0 + y * (9494680.718
               + y * (59272.64853 + y * (267.8532712 + y)))))
    small = p_small / q_small
    # large |x|
    z = 8.0 / jnp.maximum(ax, 1e-30)
    y2 = z * z
    xx = ax - 0.785398164
    p1 = (1.0 + y2 * (-0.1098628627e-2 + y2 * (0.2734510407e-4
          + y2 * (-0.2073370639e-5 + y2 * 0.2093887211e-6))))
    p2 = (-0.1562499995e-1 + y2 * (0.1430488765e-3 + y2 * (-0.6911147651e-5
          + y2 * (0.7621095161e-6 + y2 * (-0.934935152e-7)))))
    large = jnp.sqrt(0.636619772 / jnp.maximum(ax, 1e-30)) * (
        jnp.cos(xx) * p1 - z * jnp.sin(xx) * p2)
    return jnp.where(ax < 8.0, small, large)


def _bessel_j1(x):
    """Abramowitz & Stegun 9.4.4/9.4.6 rational approximations."""
    ax = jnp.abs(x)
    y = x * x
    p_small = x * (72362614232.0 + y * (-7895059235.0 + y * (242396853.1
              + y * (-2972611.439 + y * (15704.48260 + y * (-30.16036606))))))
    q_small = (144725228442.0 + y * (2300535178.0 + y * (18583304.74
              + y * (99447.43394 + y * (376.9991397 + y)))))
    small = p_small / q_small
    z = 8.0 / jnp.maximum(ax, 1e-30)
    y2 = z * z
    xx = ax - 2.356194491
    p1 = (1.0 + y2 * (0.183105e-2 + y2 * (-0.3516396496e-4
          + y2 * (0.2457520174e-5 + y2 * (-0.240337019e-6)))))
    p2 = (0.04687499995 + y2 * (-0.2002690873e-3 + y2 * (0.8449199096e-5
          + y2 * (-0.88228987e-6 + y2 * 0.105787412e-6))))
    large = jnp.sqrt(0.636619772 / jnp.maximum(ax, 1e-30)) * (
        jnp.cos(xx) * p1 - z * jnp.sin(xx) * p2) * jnp.sign(x)
    return jnp.where(ax < 8.0, small, large)


def ring(u, v, w, eX, cxi, sxi, cphi, sphi):
    """predict.c:222 — J0(2*pi*|uv_projected|*eX); always projected."""
    up = u * cxi - v * cphi * sxi + w * sphi * sxi
    vp = u * sxi + v * cphi * cxi - w * sphi * cxi
    b = jnp.sqrt(up * up + vp * vp) * eX * 2.0 * jnp.pi
    return _bessel_j0(b)


def disk(u, v, w, eX, cxi, sxi, cphi, sphi):
    """predict.c:237 — J1(2*pi*|uv_projected|*eX); always projected."""
    up = u * cxi - v * cphi * sxi + w * sphi * sxi
    vp = u * sxi + v * cphi * cxi - w * sphi * cxi
    b = jnp.sqrt(up * up + vp * vp) * eX * 2.0 * jnp.pi
    return _bessel_j1(b)


def _hermite_basis(x, n0max: int):
    """Shapelet 1-D basis B_n(x) = H_n(x) exp(-x^2/2)/sqrt(2^(n+1) n!).

    Same normalization as predict.c:86-92 (note its sqrt(2<<n * n!) =
    sqrt(2^(n+1) n!)). Returns [..., n0max]. Physicists' Hermite recursion
    unrolled at trace time (n0max is static).
    """
    hs = [jnp.ones_like(x)]
    if n0max > 1:
        hs.append(2.0 * x)
    for n in range(2, n0max):
        hs.append(2.0 * x * hs[n - 1] - 2.0 * (n - 1) * hs[n - 2])
    fact = 1.0
    norms = []
    for n in range(n0max):
        if n > 0:
            fact *= n
        norms.append(1.0 / np.sqrt(float(2 ** (n + 1)) * fact))
    expv = jnp.exp(-0.5 * x * x)
    return jnp.stack([h * (expv * nrm) for h, nrm in zip(hs, norms)], axis=-1)


def shapelet_sign_tables(n0max: int):
    """(sign, is_imag) [n0max, n0max] numpy tables for mode (n1, n2).

    Mode parity: i^(n1+n2) folded into a real/imag split with sign
    (predict.c:110-121).
    """
    n1 = np.arange(n0max)[:, None]
    n2 = np.arange(n0max)[None, :]
    tot = n1 + n2
    is_imag = (tot % 2).astype(np.float64)
    sign = np.where(is_imag == 0,
                    np.where(((tot // 2) % 2) == 0, 1.0, -1.0),
                    np.where((((tot - 1) // 2) % 2) == 0, 1.0, -1.0))
    return sign, is_imag


def shapelet(u, v, w, eX, eY, eP, beta, modes, n0, n0max: int,
             cxi, sxi, cphi, sphi, use_projection):
    """predict.c:142 — complex envelope 2*pi*(Re + i*Im)*a*b.

    ``modes`` is [..., n0max^2] zero-padded; ``n0`` the per-source live mode
    count (modes beyond n0^2 are zero so no explicit mask is needed).
    Evaluates the Fourier-domain Hermite basis at (-ut, vt) as the reference
    does (it decomposes f(-l, m)).
    """
    up, vp = _project_uv(u, v, w, cxi, sxi, cphi, sphi, use_projection,
                         negate=True)
    a = 1.0 / jnp.where(eX != 0, eX, 1.0)
    b = 1.0 / jnp.where(eY != 0, eY, 1.0)
    sinph, cosph = jnp.sin(eP), jnp.cos(eP)
    ut = a * (cosph * up - sinph * vp)
    vt = b * (sinph * up + cosph * vp)

    bu = _hermite_basis(-ut * beta, n0max)          # [..., n0max] (n1 axis)
    bv = _hermite_basis(vt * beta, n0max)           # [..., n0max] (n2 axis)
    sign, is_imag = shapelet_sign_tables(n0max)
    # mode value for (n1, n2): sign * bu[n1] * bv[n2]
    grid = bu[..., None, :] * bv[..., :, None]      # [..., n2, n1]
    grid = grid * jnp.asarray(sign.T, grid.dtype)   # sign[n1,n2] -> [n2,n1]
    m = modes.reshape(modes.shape[:-1] + (n0max, n0max))  # [..., n2, n1]
    contrib = m * grid
    imag_mask = jnp.asarray(is_imag.T, grid.dtype)
    realsum = jnp.sum(contrib * (1.0 - imag_mask), axis=(-1, -2))
    imagsum = jnp.sum(contrib * imag_mask, axis=(-1, -2))
    return 2.0 * jnp.pi * (realsum + 1j * imagsum) * a * b


def apply_envelopes(phasor, stype, u, v, w, eX, eY, eP, cxi, sxi, cphi, sphi,
                    use_projection, sh_beta, sh_modes, sh_n0, n0max: int,
                    with_shapelets: bool = True):
    """Multiply a per-source phasor by its morphology envelope.

    ``phasor`` and all source params broadcast to a common [..., S] shape;
    u,v,w are in wavelengths. ``with_shapelets`` statically elides the
    (expensive) shapelet basis when the model has none.
    """
    env = jnp.ones_like(phasor)
    env = jnp.where(stype == STYPE_GAUSSIAN,
                    gaussian(u, v, w, eX, eY, eP, cxi, sxi, cphi, sphi,
                             use_projection).astype(env.dtype), env)
    env = jnp.where(stype == STYPE_RING,
                    ring(u, v, w, eX, cxi, sxi, cphi, sphi).astype(env.dtype),
                    env)
    env = jnp.where(stype == STYPE_DISK,
                    disk(u, v, w, eX, cxi, sxi, cphi, sphi).astype(env.dtype),
                    env)
    out = phasor * env
    if with_shapelets:
        sh = shapelet(u, v, w, eX, eY, eP, sh_beta, sh_modes, sh_n0, n0max,
                      cxi, sxi, cphi, sphi, use_projection)
        out = jnp.where(stype == STYPE_SHAPELET, phasor * sh.astype(out.dtype),
                        out)
    return out
