"""Visibility prediction (the RIME) in JAX.

Capability parity with reference ``src/lib/Radio``:
- ``precalculate_coherencies`` predict.c:653 / ``_multifreq`` predict.c:890
- ``predict_visibilities`` predict.c:417
- model prediction with solutions + residual subtraction residual.c:930,1242
- GPU variant predict_model.cu:850 (``kernel_coherencies``)

Re-architected TPU-first: instead of a pthread pool over baseline ranges
calling per-source scalar functions, the whole (cluster, baseline, channel,
source) product is one vectorized masked computation. Clusters are mapped
with ``lax.map`` (peak memory [S, B] per cluster) and everything inside
fuses into a handful of XLA kernels on the MXU/VPU.

Conventions (identical to reference):
- u,v,w in SECONDS (meters/c); multiply by frequency for wavelengths.
- fringe phase 2*pi*(u l + v m + w n) * f with n carrying the -1.
- channel smearing |sinc(G * fdelta/2)|; time smearing exists in the
  reference only as dead code (residual.c:429) and is likewise omitted.
- coherencies (solve path) use fluxes pre-scaled to the data reference
  frequency; the per-channel model (residual path) rescales from catalog
  values per channel (residual.c:453-478).
- Stokes -> correlations: [[I+Q, U+iV], [U-iV, I-Q]] (predict.c:385-390).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.rime import envelopes
from sagecal_tpu.skymodel import ClusterSky, STYPE_SHAPELET


class SkyArrays(NamedTuple):
    """Device-resident padded sky model (pytree of [M, Smax] arrays)."""

    ll: jax.Array
    mm: jax.Array
    nn: jax.Array
    ra: jax.Array
    dec: jax.Array
    sI: jax.Array
    sQ: jax.Array
    sU: jax.Array
    sV: jax.Array
    sI0: jax.Array
    sQ0: jax.Array
    sU0: jax.Array
    sV0: jax.Array
    spec_idx: jax.Array
    spec_idx1: jax.Array
    spec_idx2: jax.Array
    f0: jax.Array
    stype: jax.Array
    eX: jax.Array
    eY: jax.Array
    eP: jax.Array
    cxi: jax.Array
    sxi: jax.Array
    cphi: jax.Array
    sphi: jax.Array
    use_projection: jax.Array
    sh_n0: jax.Array
    sh_beta: jax.Array
    sh_modes: jax.Array
    smask: jax.Array


def sky_to_device(sky: ClusterSky, real_dtype=jnp.float32) -> SkyArrays:
    f = lambda a: jnp.asarray(a, real_dtype)
    return SkyArrays(
        ll=f(sky.ll), mm=f(sky.mm), nn=f(sky.nn),
        ra=f(sky.ra), dec=f(sky.dec),
        sI=f(sky.sI), sQ=f(sky.sQ), sU=f(sky.sU), sV=f(sky.sV),
        sI0=f(sky.sI0), sQ0=f(sky.sQ0), sU0=f(sky.sU0), sV0=f(sky.sV0),
        spec_idx=f(sky.spec_idx), spec_idx1=f(sky.spec_idx1),
        spec_idx2=f(sky.spec_idx2), f0=f(sky.f0),
        stype=jnp.asarray(sky.stype, jnp.int32),
        eX=f(sky.eX), eY=f(sky.eY), eP=f(sky.eP),
        cxi=f(sky.cxi), sxi=f(sky.sxi), cphi=f(sky.cphi), sphi=f(sky.sphi),
        use_projection=jnp.asarray(sky.use_projection, bool),
        sh_n0=jnp.asarray(sky.sh_n0, jnp.int32),
        sh_beta=f(sky.sh_beta), sh_modes=f(sky.sh_modes),
        smask=jnp.asarray(sky.smask, bool),
    )


def _spectral_flux(s0, spec_idx, spec_idx1, spec_idx2, f0, freq):
    """Catalog flux -> flux at ``freq`` (residual.c:453-478 semantics:
    scaling applies only where spec_idx != 0; sign passes through)."""
    fr = jnp.log(freq / f0)
    tempfr = spec_idx * fr + spec_idx1 * fr * fr + spec_idx2 * fr ** 3
    mag = jnp.exp(jnp.log(jnp.maximum(jnp.abs(s0), 1e-300)) + tempfr)
    scaled = jnp.where(s0 == 0.0, 0.0, jnp.sign(s0) * mag)
    return jnp.where(spec_idx != 0.0, scaled, s0)


def _cluster_coherency(csky, u, v, w, freqs, fdelta, per_channel_flux: bool,
                       n0max: int, with_shapelets: bool,
                       af=None, E=None, tslot=None, sta1=None, sta2=None):
    """Coherencies of ONE cluster: [B, F, 2, 2] complex.

    ``csky`` is a SkyArrays row (arrays [S]); u,v,w [B] seconds; freqs [F].
    Beam (predict_withbeam.c:139-187): ``af`` [F, S, T, N] array-factor
    gains multiply each source's amplitude by af_p*af_q; ``E`` [S, T, N,
    2, 2] element E-Jones sandwich each source's brightness E_p B E_q^H.
    ``tslot``/``sta1``/``sta2`` [B] map data rows to (time, antennas).
    """
    cdtype = jnp.complex64 if u.dtype == jnp.float32 else jnp.complex128
    # G [B, S]: frequency-independent phase term (seconds)
    G = 2.0 * jnp.pi * (u[:, None] * csky.ll[None, :]
                        + v[:, None] * csky.mm[None, :]
                        + w[:, None] * csky.nn[None, :])
    if E is not None:
        Et = jnp.moveaxis(E, (0, 1, 2), (2, 0, 1))      # [T, N, S, 2, 2]
        E1 = Et[tslot, sta1]                            # [B, S, 2, 2]
        E2 = Et[tslot, sta2]

    def one_channel(freq, af_f=None):
        # f32 fringe phases match the reference's float GPU predict path
        # (predict_model.cu); pass f64 u,v,w for reference-CPU precision.
        phase = G * freq
        phasor = jax.lax.complex(jnp.cos(phase), jnp.sin(phase)).astype(cdtype)
        smfac = G * (fdelta * 0.5)
        smear = jnp.where(jnp.abs(G) > 0,
                          jnp.abs(jnp.sinc(smfac / jnp.pi)), 1.0)
        phasor = phasor * smear.astype(cdtype)
        # wavelengths for envelopes
        ul, vl, wl = u[:, None] * freq, v[:, None] * freq, w[:, None] * freq
        phasor = envelopes.apply_envelopes(
            phasor, csky.stype[None, :], ul, vl, wl,
            csky.eX[None, :], csky.eY[None, :], csky.eP[None, :],
            csky.cxi[None, :], csky.sxi[None, :], csky.cphi[None, :],
            csky.sphi[None, :], csky.use_projection[None, :],
            csky.sh_beta[None, :], csky.sh_modes[None, :, :],
            csky.sh_n0[None, :], n0max, with_shapelets)
        if af_f is not None:
            aft = jnp.moveaxis(af_f, 0, -1)             # [T, N, S]
            phasor = phasor * (aft[tslot, sta1]
                               * aft[tslot, sta2]).astype(cdtype)
        if per_channel_flux:
            sI = _spectral_flux(csky.sI0, csky.spec_idx, csky.spec_idx1,
                                csky.spec_idx2, csky.f0, freq)
            sQ = _spectral_flux(csky.sQ0, csky.spec_idx, csky.spec_idx1,
                                csky.spec_idx2, csky.f0, freq)
            sU = _spectral_flux(csky.sU0, csky.spec_idx, csky.spec_idx1,
                                csky.spec_idx2, csky.f0, freq)
            sV = _spectral_flux(csky.sV0, csky.spec_idx, csky.spec_idx1,
                                csky.spec_idx2, csky.f0, freq)
        else:
            sI, sQ, sU, sV = csky.sI, csky.sQ, csky.sU, csky.sV
        live = csky.smask
        phasor = jnp.where(live[None, :], phasor, 0.0)
        b00 = (sI + sQ).astype(cdtype)
        b01 = (sU + 1j * sV).astype(cdtype)
        b10 = (sU - 1j * sV).astype(cdtype)
        b11 = (sI - sQ).astype(cdtype)
        if E is None:
            xx = jnp.sum(phasor * b00[None, :], axis=1)
            xy = jnp.sum(phasor * b01[None, :], axis=1)
            yx = jnp.sum(phasor * b10[None, :], axis=1)
            yy = jnp.sum(phasor * b11[None, :], axis=1)
            return jnp.stack([jnp.stack([xx, xy], -1),
                              jnp.stack([yx, yy], -1)], -2)  # [B, 2, 2]
        # element beam: per-source 2x2 sandwich, then sum over sources
        Bm = jnp.stack([jnp.stack([b00, b01], -1),
                        jnp.stack([b10, b11], -1)], -2)      # [S, 2, 2]
        Bm = phasor[..., None, None] * Bm[None]              # [B, S, 2, 2]
        return jnp.einsum("bsij,bsjk,bslk->bil", E1, Bm, jnp.conj(E2))

    if af is None:
        out = jax.vmap(lambda f: one_channel(f), out_axes=1)(freqs)
    else:
        out = jax.vmap(one_channel, out_axes=1)(freqs, af)
    return out  # [B, F, 2, 2]


def coherencies(sky: SkyArrays, u, v, w, freqs, fdelta,
                per_channel_flux: bool = False,
                with_shapelets: bool | None = None,
                beam=None, dobeam: int = 0,
                tslot=None, sta1=None, sta2=None):
    """All-cluster coherencies [M, B, F, 2, 2] (no Jones applied).

    Equivalent of precalculate_coherencies[_multifreq] (predict.c:653/:890);
    with ``beam`` (a :class:`sagecal_tpu.rime.beam.BeamArrays`) and
    ``dobeam`` != 0 this is precalculate_coherencies[_multifreq]_withbeam
    (predict_withbeam.c:522/:690) — beam tables are computed per cluster
    and folded into the source sum.
    ``fdelta`` is the smearing bandwidth PER CHANNEL (callers pass total
    bandwidth for channel-averaged single-freq solves, total/Nchan for
    multifreq, matching predict.c:943).
    ``with_shapelets`` defaults to auto-detect (static) from the model.
    """
    if with_shapelets is None:
        if isinstance(sky.sh_n0, jax.core.Tracer):
            # under jit we cannot inspect values; keep the general path
            with_shapelets = True
        else:
            with_shapelets = bool(np.any(np.asarray(sky.sh_n0) > 0))
    n0max = int(np.sqrt(sky.sh_modes.shape[-1]).round())
    if beam is not None and dobeam:
        from sagecal_tpu.rime import beam as beam_mod

        def per_cluster(csky):
            af, E = beam_mod.cluster_beam(beam, csky.ra, csky.dec,
                                          jnp.atleast_1d(freqs), dobeam)
            return _cluster_coherency(csky, u, v, w, freqs, fdelta,
                                      per_channel_flux, n0max,
                                      with_shapelets, af=af, E=E,
                                      tslot=tslot, sta1=sta1, sta2=sta2)
    else:
        def per_cluster(csky):
            return _cluster_coherency(csky, u, v, w, freqs, fdelta,
                                      per_channel_flux, n0max,
                                      with_shapelets)

    return jax.lax.map(per_cluster, sky)


def coherencies_split(sky_pg, sky_rest, u, v, w, freqs, fdelta,
                      per_channel_flux: bool = False):
    """Hybrid coherencies: Pallas kernel on the point/gaussian half,
    XLA on the compact repacked rest (skymodel.split_for_pallas).

    ``sky_rest`` None means the model is fully kernel-supported. The two
    halves preserve cluster order, so outputs add elementwise.
    """
    from sagecal_tpu.ops import coh_pallas
    out = coh_pallas.coherencies(sky_pg, u, v, w, freqs, fdelta,
                                 per_channel_flux=per_channel_flux)
    if sky_rest is not None:
        out = out + coherencies(sky_rest, u, v, w, freqs, fdelta,
                                per_channel_flux=per_channel_flux)
    return out


def uvcut_flags(flags, u, v, freqs, uvmin, uvmax):
    """Mark baselines outside the uv range with flag=2: still subtracted,
    excluded from the solve (predict.c:876-882, multifreq rule)."""
    freqs = jnp.atleast_1d(freqs)
    uvdist = jnp.sqrt(u * u + v * v) * freqs[0]
    out = (uvdist < uvmin) | (uvdist * freqs[-1] > uvmax * freqs[0])
    return jnp.where((flags == 0) & out, 2, flags)


def apply_uvcut(rowflags, tile, uvmin: float, uvmax: float):
    """Host-side uv-window on a COPY of a tile's row flags (the shared
    gate for every mode: full window -> unchanged input). Returns int8
    [nrows]; callers must never write the result back into the tile
    (the cut is solve-scoped, Data::loadData semantics)."""
    if not (uvmin > 0.0 or uvmax < 1e9):
        return np.asarray(rowflags)
    import numpy as _np
    return _np.asarray(uvcut_flags(
        jnp.asarray(_np.asarray(rowflags), jnp.int32),
        jnp.asarray(_np.asarray(tile.u, _np.float64)),
        jnp.asarray(_np.asarray(tile.v, _np.float64)),
        jnp.asarray(_np.asarray(tile.freqs, _np.float64)),
        uvmin, uvmax), _np.int8)


def chunk_indices(tilesz: int, nbase: int, nchunk: np.ndarray) -> np.ndarray:
    """[M, B] map from data row to hybrid time-chunk per cluster.

    Rows are ordered [tilesz, nbase] flattened; chunk ck covers timeslots
    [ck*ceil(tilesz/nchunk), ...) (lmfit.c:893-899).
    """
    t = np.arange(tilesz * nbase) // nbase
    out = np.zeros((len(nchunk), tilesz * nbase), np.int32)
    for m, K in enumerate(np.asarray(nchunk)):
        tilechunk = (tilesz + K - 1) // K
        out[m] = np.minimum(t // tilechunk, K - 1)
    return out


def model8(coh_m, J_m, sta1, sta2, chunk_idx_m, out_dtype=None):
    """One cluster's corrupted model as [B, 8] reals (solve-path data
    order: (Re, Im) of XX, XY, YX, YY — Dirac.h:1541-1546).

    ``out_dtype`` is the dtype-policy storage emission contract
    (sagecal_tpu.dtypes): the model EVALUATION is complex (c64 — J and
    the coherencies never quantize) and the emitted real stream casts
    to the storage dtype exactly where it joins the [B]-residual
    traffic; a no-op for f32/f64. The solver-side twins
    (solvers.sage._model8 / normal_eq.residual8) follow the same
    contract — this is the rime-layer entry point for embedders that
    build their own residual streams.
    """
    from sagecal_tpu import dtypes as dtp
    Jp = J_m[chunk_idx_m, sta1]
    Jq = J_m[chunk_idx_m, sta2]
    V = Jp @ coh_m @ jnp.conj(jnp.swapaxes(Jq, -1, -2))
    vf = V.reshape(-1, 4)
    out = jnp.stack([vf.real, vf.imag], -1).reshape(-1, 8)
    return out if out_dtype is None else dtp.to_storage(out, out_dtype)


def apply_jones(coh_m, J_m, sta1, sta2, chunk_idx_m):
    """One cluster's corrupted model: J_p C J_q^H per baseline.

    coh_m: [B, F, 2, 2]; J_m: [Kmax, N, 2, 2]; chunk_idx_m: [B].
    Returns [B, F, 2, 2].
    """
    Jp = J_m[chunk_idx_m, sta1]            # [B, 2, 2]
    Jq = J_m[chunk_idx_m, sta2]
    JqH = jnp.conj(jnp.swapaxes(Jq, -1, -2))
    return jnp.einsum("bij,bfjk,bkl->bfil", Jp, coh_m, JqH)


def predict_model(coh, J, sta1, sta2, chunk_idx, cluster_mask=None):
    """Sum of corrupted cluster models: sum_m J_p C_m J_q^H -> [B, F, 2, 2].

    coh: [M, B, F, 2, 2]; J: [M, Kmax, N, 2, 2]; chunk_idx: [M, B];
    cluster_mask: [M] bool (e.g. subtract mask / ignore list).
    """
    def body(carry, xs):
        coh_m, J_m, cidx_m, keep = xs
        vis = apply_jones(coh_m, J_m, sta1, sta2, cidx_m)
        return carry + jnp.where(keep, 1.0, 0.0) * vis, None

    M = coh.shape[0]
    if cluster_mask is None:
        cluster_mask = jnp.ones((M,), bool)
    init = jnp.zeros(coh.shape[1:], coh.dtype)
    out, _ = jax.lax.scan(body, init, (coh, J, chunk_idx, cluster_mask))
    return out


def predict_visibilities(sky: SkyArrays, u, v, w, freqs, fdelta,
                         per_channel_flux: bool = True,
                         cluster_mask=None, beam=None, dobeam: int = 0,
                         tslot=None, sta1=None, sta2=None):
    """Uncorrupted model visibilities summed over clusters [B, F, 2, 2]
    (predict.c:417 / residual.c:1242 simulation path; with beam:
    predict_visibilities_multifreq_withbeam, predict_withbeam.c:1155)."""
    coh = coherencies(sky, u, v, w, freqs, fdelta,
                      per_channel_flux=per_channel_flux,
                      beam=beam, dobeam=dobeam,
                      tslot=tslot, sta1=sta1, sta2=sta2)
    if cluster_mask is not None:
        coh = jnp.where(cluster_mask[:, None, None, None, None], coh, 0.0)
    return jnp.sum(coh, axis=0)
