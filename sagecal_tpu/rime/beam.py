"""Station beam models: geometric array factor + spherical element beam.

Capability parity with reference ``src/lib/Radio``:
- ``arraybeam`` (stationbeam.c:44): per-(source, time, station[, freq])
  scalar array-factor gain — geometric-delay beamforming over station
  elements, beamformed at ``f0`` toward (ra0, dec0), evaluated at ``f``
  toward the source; gain = |mean_k exp(-i 2pi/c r.p_k)|, 0 below horizon.
- ``element_beam`` / ``array_element_beam`` (stationbeam.c:119-260):
  per-(source, time, station) 2x2 complex E-Jones from a dual-pol
  Zernike-like polar basis (elementbeam.c ``eval_elementcoeffs``):
  mode (n, m), m = -n..n step 2, basis = preamble * (pi/4+r)^|m|
  * L_{(n-|m|)/2}^{|m|}(r^2/b^2) * exp(-r^2/2b^2) * exp(-i m theta),
  E = [[X.theta, X.phi], [Y.theta, Y.phi]] with X at (zd, az-pi/4) and
  Y at (zd, az+pi/4).
- ``set_elementcoeffs`` (elementbeam.c:39): frequency interpolation of the
  per-band coefficient tables. The reference hardcodes LOFAR LBA/HBA
  characterization tables; this framework treats coefficients as DATA —
  loadable from .npz — and ships synthetic dipole-fit defaults with the
  same basis/order so the full code path runs without proprietary tables
  (convert real tables with :func:`save_element_coeffs`).

TPU-first design: everything is batched over (source, time, station)
and jit-traceable; the element-basis mode loop (28 modes for order 7)
unrolls at trace time into fused elementwise ops. Beam tables feed the
coherency product in :mod:`sagecal_tpu.rime.predict` exactly where the
reference's precomputed ``beamgain``/``elementgain`` tables feed
predict_withbeam.c:139-187.

Beam modes follow Dirac_common.h:97-109: NONE=0, ARRAY=1, FULL=2,
ELEMENT=3.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu import coords

C_M_S = 299792458.0

DOBEAM_NONE = 0
DOBEAM_ARRAY = 1
DOBEAM_FULL = 2
DOBEAM_ELEMENT = 3

BEAM_ELEM_MODES = 7     # polynomial order M; Nmodes = M(M+1)/2 = 28
BEAM_ELEM_BETA = 0.5


# ---------------------------------------------------------------------------
# element-beam coefficient tables (host side)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElementCoeffs:
    """Dual-pol element-pattern coefficients on a frequency grid.

    theta/phi: [Nfreq, Nmodes] complex; freqs in Hz.
    """

    freqs: np.ndarray
    theta: np.ndarray
    phi: np.ndarray
    M: int = BEAM_ELEM_MODES
    beta: float = BEAM_ELEM_BETA

    @property
    def n_modes(self) -> int:
        return self.M * (self.M + 1) // 2


def mode_table(M: int):
    """(n, m, p=(n-|m|)/2, |m|) per mode, the basis enumeration of
    elementbeam.c:147-158."""
    n_l, m_l = [], []
    for n in range(M):
        for m in range(-n, n + 1, 2):
            n_l.append(n)
            m_l.append(m)
    n_a = np.asarray(n_l)
    m_a = np.asarray(m_l)
    absm = np.abs(m_a)
    return n_a, m_a, (n_a - absm) // 2, absm


def mode_preamble(M: int, beta: float) -> np.ndarray:
    """Per-mode normalization (elementbeam.c:146-159):
    (-1)^((n-|m|)/2) sqrt(((n-|m|)/2)! / (pi ((n+|m|)/2)!)) / beta^(1+|m|).
    """
    n_a, _, p_a, absm = mode_table(M)
    out = np.empty(len(n_a))
    for i, (p, q) in enumerate(zip(p_a, (n_a + absm) // 2)):
        out[i] = math.sqrt(math.factorial(p) / (math.pi * math.factorial(q)))
        if p % 2:
            out[i] = -out[i]
        out[i] *= beta ** (-1.0 - absm[i])
    return out


def _laguerre(p: int, q: int, x):
    """Generalized Laguerre L_p^q(x), ascending recursion
    (elementbeam.c:176-196). p is a small static int."""
    if p == 0:
        return jnp.ones_like(x)
    lm2 = jnp.ones_like(x)
    lm1 = 1.0 + q - x
    if p == 1:
        return lm1
    for i in range(2, p + 1):
        inv = 1.0 / i
        cur = (2.0 + inv * (q - 1.0 - x)) * lm1 - (1.0 + inv * (q - 1)) * lm2
        lm2, lm1 = lm1, cur
    return lm1


def element_basis(r, theta, M: int, beta: float):
    """Basis functions at polar (r=zenith angle, theta=rotated azimuth).

    Returns [..., Nmodes] complex (eval_elementcoeffs, elementbeam.c:198-235).
    """
    _, m_a, p_a, absm = mode_table(M)
    pre = mode_preamble(M, 1.0)  # beta-free part; beta applied via jnp below
    rb = (r / beta) ** 2
    ex = jnp.exp(-0.5 * rb)
    cols = []
    for i in range(len(m_a)):
        lg = _laguerre(int(p_a[i]), int(absm[i]), rb)
        rm = (jnp.pi / 4.0 + r) ** int(absm[i])
        bscale = beta ** (-1.0 - int(absm[i]))
        pr = rm * lg * ex * (pre[i] * bscale)
        ang = -float(m_a[i]) * theta
        cols.append(pr * jax.lax.complex(jnp.cos(ang), jnp.sin(ang)))
    return jnp.stack(cols, axis=-1)


def synthetic_element_coeffs(band: str = "lba", M: int = BEAM_ELEM_MODES,
                             beta: float = BEAM_ELEM_BETA,
                             n_freqs: int = 10) -> ElementCoeffs:
    """Fit the polar basis to an analytic crossed-dipole pattern.

    Stand-in for the hardcoded LOFAR characterization tables
    (elementcoeff.h): E_theta ~ cos(zd) cos(phi), E_phi ~ -sin(phi) with a
    gentle frequency taper, projected onto the same (M, beta) basis by
    least squares, so evaluation exercises the identical code path.
    """
    if band == "lba":
        freqs = np.linspace(10e6, 100e6, n_freqs)
    else:
        freqs = np.linspace(110e6, 250e6, n_freqs)
    rr = np.linspace(0.0, np.pi / 2, 24)
    tt = np.linspace(0.0, 2 * np.pi, 33)[:-1]
    Rg, Tg = np.meshgrid(rr, tt, indexing="ij")
    A = np.asarray(element_basis(jnp.asarray(Rg.ravel()),
                                 jnp.asarray(Tg.ravel()), M, beta))
    th_tab = np.empty((n_freqs, A.shape[1]), complex)
    ph_tab = np.empty((n_freqs, A.shape[1]), complex)
    fmid = freqs.mean()
    for i, f in enumerate(freqs):
        taper = np.cos(Rg.ravel()) ** (1.0 + 0.5 * (f - fmid) / fmid)
        e_th = taper * np.cos(Tg.ravel()) * (1.0 + 0.1j * (f - fmid) / fmid)
        e_ph = -np.sin(Tg.ravel()) * (1.0 - 0.05j * (f - fmid) / fmid)
        th_tab[i], *_ = np.linalg.lstsq(A, e_th, rcond=None)[:1]
        ph_tab[i], *_ = np.linalg.lstsq(A, e_ph, rcond=None)[:1]
    return ElementCoeffs(freqs=freqs, theta=th_tab, phi=ph_tab,
                         M=M, beta=beta)


_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def lofar_element_coeffs(band: str) -> ElementCoeffs:
    """Measured LOFAR LBA/HBA element characterization tables.

    Converted from the reference's auto-generated coefficient data
    (elementcoeff.h: 10 LBA / 15 HBA frequencies x 28 modes, M=7,
    beta=0.5) by tools_dev/convert_elementcoeff.py; frequencies stored in
    Hz. Selection by band follows the <100 MHz LBA/HBA split of the
    callers (fullbatch_mode.cpp:71).
    """
    return load_element_coeffs(
        os.path.join(_DATA_DIR, f"lofar_elem_{band}.npz"))


def default_element_coeffs(band: str) -> ElementCoeffs:
    """The LOFAR characterization tables; synthetic dipole fit only if
    the packaged data files are missing."""
    try:
        return lofar_element_coeffs(band)
    except (FileNotFoundError, OSError):        # pragma: no cover
        return synthetic_element_coeffs(band)


def save_element_coeffs(path: str, ecoeff: ElementCoeffs) -> None:
    np.savez(path, freqs=ecoeff.freqs, theta=ecoeff.theta, phi=ecoeff.phi,
             M=ecoeff.M, beta=ecoeff.beta)


def load_element_coeffs(path: str) -> ElementCoeffs:
    z = np.load(path)
    return ElementCoeffs(freqs=z["freqs"], theta=z["theta"], phi=z["phi"],
                         M=int(z["M"]), beta=float(z["beta"]))


def element_pattern_at(ecoeff: ElementCoeffs, freq_hz: float):
    """Interpolate pattern vectors to ``freq_hz`` (set_elementcoeffs
    elementbeam.c:80-103: linear blend of the two bracketing table rows,
    clamped at the ends)."""
    f = ecoeff.freqs
    if freq_hz <= f[0]:
        return ecoeff.theta[0].copy(), ecoeff.phi[0].copy()
    if freq_hz >= f[-1]:
        return ecoeff.theta[-1].copy(), ecoeff.phi[-1].copy()
    ih = int(np.searchsorted(f, freq_hz))
    il = ih - 1
    wl = freq_hz - f[il]
    wh = f[ih] - freq_hz
    w1 = wl / (wl + wh)
    th = (1.0 - w1) * ecoeff.theta[il] + w1 * ecoeff.theta[ih]
    ph = (1.0 - w1) * ecoeff.phi[il] + w1 * ecoeff.phi[ih]
    return th, ph


# ---------------------------------------------------------------------------
# beam geometry (host container + device arrays)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BeamInfo:
    """Host-side station/beam metadata (readAuxData with beam,
    src/MS/data.cpp:194: station long/lat, element offsets, times)."""

    longitude: np.ndarray        # [N] rad
    latitude: np.ndarray         # [N] rad
    time_jd: np.ndarray          # [T] JD (days)
    ra0: float                   # beam pointing (rad)
    dec0: float
    freq0: float                 # beamformer reference freq (Hz)
    elem_xyz: np.ndarray         # [N, Emax, 3] element positions (m)
    elem_mask: np.ndarray        # [N, Emax] bool
    ecoeff: ElementCoeffs | None = None


class BeamArrays(NamedTuple):
    """Device-resident beam model (pytree)."""

    longitude: jax.Array         # [N]
    latitude: jax.Array          # [N]
    gmst: jax.Array              # [T] degrees (precomputed from time_jd)
    ra0: jax.Array
    dec0: jax.Array
    freq0: jax.Array
    elem_xyz: jax.Array          # [N, Emax, 3]
    elem_mask: jax.Array         # [N, Emax]
    n_elem: jax.Array            # [N]
    patt_theta: jax.Array        # [Nmodes, 2] re/im (at data freq0) —
    patt_phi: jax.Array          # stored real: complex arrays cannot cross
    elem_beta: jax.Array         # host<->device on the axon TPU runtime


def beam_to_device(info: BeamInfo, data_freq0: float | None = None,
                   real_dtype=jnp.float32, time_jd=None) -> BeamArrays:
    """Stage beam metadata onto the device. The element pattern is
    interpolated once at the data reference frequency (fullbatch_mode.cpp:70
    calls set_elementcoeffs with iodata.freq0). ``time_jd`` overrides the
    stored times (per-tile staging in the streaming pipeline)."""
    f = lambda a: jnp.asarray(a, real_dtype)
    f0ref = data_freq0 or info.freq0
    ecoeff = info.ecoeff or default_element_coeffs(band_for_freq(f0ref))
    th, ph = element_pattern_at(ecoeff, f0ref)
    th = np.stack([th.real, th.imag], axis=-1)
    ph = np.stack([ph.real, ph.imag], axis=-1)
    gmst = coords.jd2gmst_np(
        info.time_jd if time_jd is None else time_jd)
    return BeamArrays(
        longitude=f(info.longitude), latitude=f(info.latitude),
        gmst=f(gmst),
        ra0=f(info.ra0), dec0=f(info.dec0), freq0=f(info.freq0),
        elem_xyz=f(info.elem_xyz), elem_mask=jnp.asarray(info.elem_mask, bool),
        n_elem=jnp.sum(info.elem_mask, axis=1).astype(real_dtype),
        patt_theta=f(th), patt_phi=f(ph),
        elem_beta=f(ecoeff.beta),
    )


def synthetic_beam(n_stations: int, time_jd, ra0: float, dec0: float,
                   freq0: float, n_elem: int = 24, extent_m: float = 30.0,
                   band: str = "lba", seed: int = 5,
                   ecoeff: ElementCoeffs | None = None) -> BeamInfo:
    """LOFAR-like synthetic beam metadata for simulation/tests: stations
    scattered near the LOFAR core, elements on a horizontal disc."""
    rng = np.random.default_rng(seed)
    lon0, lat0 = 0.12, 0.92   # ~LOFAR core (rad)
    longitude = lon0 + 1e-4 * rng.normal(size=n_stations)
    latitude = lat0 + 1e-4 * rng.normal(size=n_stations)
    r = extent_m * np.sqrt(rng.random((n_stations, n_elem)))
    th = 2 * np.pi * rng.random((n_stations, n_elem))
    elem = np.stack([r * np.cos(th), r * np.sin(th),
                     np.zeros_like(r)], axis=-1)
    mask = np.ones((n_stations, n_elem), bool)
    return BeamInfo(longitude=longitude, latitude=latitude,
                    time_jd=np.atleast_1d(np.asarray(time_jd, float)),
                    ra0=ra0, dec0=dec0, freq0=freq0,
                    elem_xyz=elem, elem_mask=mask,
                    ecoeff=ecoeff or default_element_coeffs(band))


def band_for_freq(freq_hz: float) -> str:
    """LBA below the ~100 MHz FM gap, HBA above (elementbeam.c table
    selection by ELEM_LBA/ELEM_HBA)."""
    return "lba" if freq_hz < 105e6 else "hba"


def resolve_beaminfo(dobeam: int, ms, meta: dict, log=print):
    """Beam metadata for a dataset: stored beam.npz, else a synthetic
    layout (loudly — a fabricated array is fine for simulation and tests
    but meaningless for real instrument data)."""
    if not dobeam:
        return None
    info = ms.beam_info()
    if info is None:
        log("WARNING: beam enabled (-B) but the dataset stores no beam "
            "metadata (beam.npz); using a SYNTHETIC station/element "
            "layout — solutions will not correspond to a real instrument")
        info = synthetic_beam(
            meta["n_stations"], np.array([2451545.0]), meta["ra0"],
            meta["dec0"], meta["freq0"], band=band_for_freq(meta["freq0"]))
    return info


def save_beaminfo(path: str, info: BeamInfo) -> None:
    """Persist beam metadata next to a dataset (the SimMS analogue of the
    MS's LOFAR_ANTENNA_FIELD subtable, data.cpp:194-300)."""
    ec = info.ecoeff or default_element_coeffs(band_for_freq(info.freq0))
    np.savez(path, longitude=info.longitude, latitude=info.latitude,
             time_jd=info.time_jd, ra0=info.ra0, dec0=info.dec0,
             freq0=info.freq0, elem_xyz=info.elem_xyz,
             elem_mask=info.elem_mask, ec_freqs=ec.freqs, ec_theta=ec.theta,
             ec_phi=ec.phi, ec_M=ec.M, ec_beta=ec.beta)


def load_beaminfo(path: str) -> BeamInfo:
    z = np.load(path)
    ec = ElementCoeffs(freqs=z["ec_freqs"], theta=z["ec_theta"],
                       phi=z["ec_phi"], M=int(z["ec_M"]),
                       beta=float(z["ec_beta"]))
    return BeamInfo(longitude=z["longitude"], latitude=z["latitude"],
                    time_jd=z["time_jd"], ra0=float(z["ra0"]),
                    dec0=float(z["dec0"]), freq0=float(z["freq0"]),
                    elem_xyz=z["elem_xyz"], elem_mask=z["elem_mask"],
                    ecoeff=ec)


# ---------------------------------------------------------------------------
# device-side evaluation
# ---------------------------------------------------------------------------

def _direction_components(az, el):
    """(sin t cos p, sin t sin p, cos t) with t=pi/2-el, p=-az
    (stationbeam.c:63-67)."""
    theta = jnp.pi / 2 - el
    st, ct = jnp.sin(theta), jnp.cos(theta)
    sp, cp = jnp.sin(-az), jnp.cos(-az)
    return st * cp, st * sp, ct


def array_factor(beam: BeamArrays, ra, dec, freq):
    """Array-factor gains [S, T, N] for source directions (ra, dec) [S] at
    one frequency (arraybeam, stationbeam.c:44-110)."""
    az, el = coords.radec2azel_gmst(
        ra[:, None, None], dec[:, None, None],
        beam.longitude[None, None, :], beam.latitude[None, None, :],
        beam.gmst[None, :, None])                       # [S, T, N]
    az0, el0 = coords.radec2azel_gmst(
        beam.ra0, beam.dec0,
        beam.longitude[None, None, :], beam.latitude[None, None, :],
        beam.gmst[None, :, None])                       # [1, T, N]
    sx, sy, sz = _direction_components(az, el)
    s0x, s0y, s0z = _direction_components(az0, el0)
    r1 = beam.freq0 * s0x - freq * sx                   # [S, T, N]
    r2 = beam.freq0 * s0y - freq * sy
    r3 = beam.freq0 * s0z - freq * sz
    tpc = 2.0 * jnp.pi / C_M_S
    # phase over elements: [S, T, N, E]
    ph = -tpc * (r1[..., None] * beam.elem_xyz[None, None, :, :, 0]
                 + r2[..., None] * beam.elem_xyz[None, None, :, :, 1]
                 + r3[..., None] * beam.elem_xyz[None, None, :, :, 2])
    m = beam.elem_mask[None, None]
    cs = jnp.sum(jnp.where(m, jnp.cos(ph), 0.0), axis=-1)
    sn = jnp.sum(jnp.where(m, jnp.sin(ph), 0.0), axis=-1)
    gain = jnp.sqrt(cs * cs + sn * sn) / beam.n_elem[None, None, :]
    return jnp.where(el >= 0.0, gain, 0.0)


def element_jones(beam: BeamArrays, ra, dec):
    """Element-beam E-Jones [S, T, N, 2, 2] complex for source directions
    (ra, dec) [S] (element_beam, stationbeam.c:215-260):
    E = [[X.theta, X.phi], [Y.theta, Y.phi]], X at (zd, az-pi/4),
    Y rotated +pi/2; zero below horizon."""
    az, el = coords.radec2azel_gmst(
        ra[:, None, None], dec[:, None, None],
        beam.longitude[None, None, :], beam.latitude[None, None, :],
        beam.gmst[None, :, None])                       # [S, T, N]
    zd = jnp.pi / 2 - el
    # Nmodes = M(M+1)/2 -> recover the (static) basis order from the shape
    M = int(round((math.isqrt(8 * beam.patt_theta.shape[0] + 1) - 1) / 2))
    bx = element_basis(zd, az - jnp.pi / 4, M, beam.elem_beta)
    by = element_basis(zd, az + jnp.pi / 4, M, beam.elem_beta)
    patt_t = jax.lax.complex(beam.patt_theta[:, 0], beam.patt_theta[:, 1])
    patt_p = jax.lax.complex(beam.patt_phi[:, 0], beam.patt_phi[:, 1])
    ex_t = jnp.sum(bx * patt_t, axis=-1)
    ex_p = jnp.sum(bx * patt_p, axis=-1)
    ey_t = jnp.sum(by * patt_t, axis=-1)
    ey_p = jnp.sum(by * patt_p, axis=-1)
    E = jnp.stack([jnp.stack([ex_t, ex_p], -1),
                   jnp.stack([ey_t, ey_p], -1)], -2)
    return jnp.where((el >= 0.0)[..., None, None], E,
                     jnp.zeros_like(E))


def cluster_beam(beam: BeamArrays, ra_s, dec_s, freqs, dobeam: int):
    """Per-cluster beam tables: (af [F, S, T, N] or None,
    E [S, T, N, 2, 2] or None), the analogue of the reference's
    ``beamgain``/``elementgain`` precompute (predict_withbeam.c:476-510)."""
    af = None
    E = None
    if dobeam in (DOBEAM_ARRAY, DOBEAM_FULL):
        af = jax.vmap(lambda f: array_factor(beam, ra_s, dec_s, f))(
            jnp.atleast_1d(freqs))
    if dobeam in (DOBEAM_ELEMENT, DOBEAM_FULL):
        E = element_jones(beam, ra_s, dec_s)
    return af, E
