"""End-to-end calibration pipelines (the application layer).

Capability parity with reference ``src/MS/fullbatch_mode.cpp``
(``run_fullbatch_calibration``:38): stream solve intervals (tiles) from the
dataset, predict solve-path coherencies, run SAGE-EM, compute/write
residuals and solutions, with the reference's convergence heuristics:

- first-tile iteration boost: 4x EM iterations for arrays <= LMCUT (=40)
  stations, 6x otherwise (fullbatch_mode.cpp:397);
- LMCUT solver downgrade: RTR/NSD modes fall back to ordered-subsets LM
  for small arrays (fullbatch_mode.cpp:397,431; sagecalmain.h:24);
- divergence reset: residual 0 / non-finite / > 5x best resets solutions
  to the initial values and re-arms the first-tile boost
  (fullbatch_mode.cpp:605-621, res_ratio fullbatch_mode.cpp:239);
- simulation modes -a 1/2/3 with optional solutions replay + ignore list
  (fullbatch_mode.cpp:524-578).

Device policy: one jitted solve program reused across tiles (shapes are
static per dataset); host streams tiles and writes residuals back.
"""

from __future__ import annotations

import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from sagecal_tpu import coords, dtypes as dtp, faults, sched, skymodel, utils
from sagecal_tpu.config import RunConfig, SimulationMode, SolverMode
from sagecal_tpu.serve import cache as pcache
from sagecal_tpu.serve import fleet as pfleet
from sagecal_tpu.serve import priors as ppriors
from sagecal_tpu.diag import trace as dtrace
from sagecal_tpu.obs import metrics as obs
from sagecal_tpu.solvers import normal_eq as ne
from sagecal_tpu.io import dataset as ds
from sagecal_tpu.io import solutions as sol
from sagecal_tpu.rime import beam as bm
from sagecal_tpu.rime import predict as rp
from sagecal_tpu.rime import residual as rr
from sagecal_tpu.solvers import lm as lm_mod
from sagecal_tpu.solvers import sage

# eager complex arithmetic is unimplemented on the axon TPU runtime; keep
# the Jones real<->complex reshapes inside jit
_jones_r2c_j = jax.jit(ne.jones_r2c)
_jones_c2r_j = jax.jit(ne.jones_c2r)

LMCUT = 40      # sagecalmain.h:24
RES_RATIO = 5.0  # fullbatch_mode.cpp:239


def _emit_tile_record(ti, res_0, res_1, mean_nu, info, minutes,
                      bubble_s=None, overlap=None):
    """Per-solve-interval convergence record (gated on an active tracer
    / metrics registry so the extra device->host syncs never run
    otherwise). ``bubble_s`` / ``overlap`` are the overlapped-execution
    accounting pair: host seconds blocked on data movement for this
    tile, and the prefetch depth it ran under (0 = synchronous
    reference loop)."""
    if not (dtrace.active() or obs.active()):
        return
    trips = lm_mod.executed_trips(info)
    if obs.active():
        obs.inc("tiles_solved_total")
        if bubble_s is not None:
            obs.inc("tile_bubble_seconds_total", float(bubble_s))
        for k, v in trips.items():
            obs.inc(f"solver_{k}_total", v)
    if not dtrace.active():
        return
    rec = dict(tile=ti, res_0=res_0, res_1=res_1, mean_nu=mean_nu,
               minutes=minutes)
    if bubble_s is not None:
        rec["bubble_s"] = float(bubble_s)
        rec["overlap"] = int(overlap or 0)
    # host-driver extras (the sharded solver reports only residuals);
    # the trace schema keeps its original two trip fields
    for k in ("solver_iters", "lbfgs_iters"):
        if k in trips:
            rec[k] = trips[k]
    dtrace.emit("tile", **rec)


def effective_solver_mode(mode: int, n_stations: int) -> int:
    """LMCUT downgrade (fullbatch_mode.cpp:397)."""
    if n_stations <= LMCUT and mode == int(SolverMode.RTR_OSLM_LBFGS):
        return int(SolverMode.OSLM_LBFGS)
    if n_stations <= LMCUT and mode in (int(SolverMode.RTR_OSRLM_RLBFGS),
                                        int(SolverMode.NSD_RLBFGS)):
        return int(SolverMode.OSLM_OSRLM_RLBFGS)
    return mode


def first_tile_boost(n_stations: int) -> int:
    return 4 if n_stations <= LMCUT else 6


class FullBatchPipeline:
    """Reusable jitted solve over a SimMS-like dataset."""

    def __init__(self, cfg: RunConfig, ms: ds.SimMS, sky: skymodel.ClusterSky,
                 real_dtype=None, log=print):
        self.cfg = cfg
        self.ms = ms
        self.sky = sky
        self.log = log
        platform = jax.devices()[0].platform
        if real_dtype is None:
            real_dtype = jnp.float64 if (
                platform == "cpu" and jax.config.read("jax_enable_x64")
            ) else jnp.float32
        self.rdt = real_dtype
        # --dtype-policy storage dtype for the staged [B]-data (x8, wt,
        # residual ring slots); "f32" keeps sdt == rdt (bit-frozen).
        # The sharded (GSPMD) path stages its [B]-rows in the storage
        # dtype too (the row-sharded solve reuses the same
        # storage/accumulate split inside sagefit) — the PR 6
        # policy-exemption melted in ISSUE 14, tolerance-gated by
        # tests/test_dtype_policy.py::test_sharded_path_applies_policy.
        policy = getattr(cfg, "dtype_policy", "f32")
        if policy != "f32" and real_dtype == jnp.float64:
            # a reduced storage policy pairs with the f32/c64 pipeline
            # (the accumulator contract is f32); keeping the f64/c128
            # CPU-test pipeline underneath would mix f64 model streams
            # into f32 solver state
            real_dtype = jnp.float32
            self.rdt = real_dtype
        self.dtype_policy = policy
        self.sdt = dtp.storage_dtype(policy, real_dtype)
        self.dsky = rp.sky_to_device(sky, real_dtype)
        meta = ms.meta
        self.kmax = int(sky.nchunk.max())
        self.cmask = np.arange(self.kmax)[None, :] < sky.nchunk[:, None]
        # --tile-bucket: pad each staged interval to a common timeslot
        # bucket (whole zero-WEIGHT timeslot blocks, serve/cache.py) so
        # bucket-compatible jobs share one set of compiled programs.
        # Every tilesz-derived static below (cidx, tslot, OS subsets)
        # is built at the BUCKET size; staging pads, residual write
        # slices the real rows back out. Exactness argument: a
        # zero-weight row contributes nothing to any weighted
        # reduction (the PR 6 OS-slicing / sharded-padding precedent).
        tb = int(getattr(cfg, "tile_bucket", 0) or 0)
        self.tilesz_eff = int(meta["tilesz"])
        if tb:
            unsupported = (cfg.per_channel_bfgs
                           or getattr(cfg, "shard_baselines", False)
                           or int(cfg.beam_mode)
                           or int(getattr(cfg, "tile_batch", 1)) > 1
                           or cfg.simulation != SimulationMode.OFF)
            if unsupported:
                log("tile-bucket: per-channel/sharded/beam/tile-batch/"
                    "simulation paths stage exact shapes; bucketing off")
            else:
                self.tilesz_eff = pcache.resolve_bucket(meta["tilesz"],
                                                        tb)
        self.pad_rows = (self.tilesz_eff - int(meta["tilesz"])) \
            * int(meta["nbase"])
        self.cidx = rp.chunk_indices(self.tilesz_eff, meta["nbase"],
                                     sky.nchunk)
        self.n = meta["n_stations"]
        self.tslot = ds.row_tslot(self.tilesz_eff * meta["nbase"],
                                  meta["nbase"])
        # beam (-B): stored metadata, else synthetic (set_elementcoeffs +
        # readAuxData-with-beam analogue; fullbatch_mode.cpp:56-70)
        self.dobeam = int(cfg.beam_mode)
        self.beam_info = bm.resolve_beaminfo(self.dobeam, ms, meta, log=log)
        self._warned_no_times = False
        # precess source + beam-pointing coordinates from J2000 to the
        # epoch of the first tile's mid timeslot, once per run
        # (precess_source_locations data.cpp:1473, called at
        # fullbatch_mode.cpp:325 only when the beam is on). Must happen
        # BEFORE any solver trace: the device sky is closure-captured as
        # jit constants.
        self.precessed = False
        if self.dobeam:
            self._precess_sources(log)
        # Pallas coherency kernel: point/gaussian f32 models on a real
        # TPU; mixed models run hybrid (kernel + compact XLA rest,
        # skymodel.split_for_pallas). The probe runs the PRODUCTION block
        # configuration (same block_b and real source count) so
        # VMEM/compile failures surface here, where we can fall back,
        # not inside the jitted solve.
        self.use_pallas = False
        self._pallas_skies = None
        # the sharded (GSPMD) solve path predicts with plain XLA — don't
        # probe/log a kernel it would silently bypass
        if (platform not in ("cpu",) and not self.dobeam
                and not getattr(cfg, "shard_baselines", False)
                and self.rdt == jnp.float32):
            from sagecal_tpu.ops import coh_pallas
            if coh_pallas.any_supported(sky):
                sky_pg, sky_rest = skymodel.split_for_pallas(sky)
                try:
                    dsky_pg = rp.sky_to_device(sky_pg, self.rdt)
                    probe_b = min(1024, meta["tilesz"] * meta["nbase"])
                    z = jnp.zeros(probe_b, jnp.float32)
                    coh_pallas.coherencies(
                        dsky_pg, z, z, z,
                        jnp.asarray([meta["freq0"]], jnp.float32),
                        meta["fdelta"]).block_until_ready()
                    self.use_pallas = True
                    self._pallas_skies = (
                        dsky_pg,
                        None if sky_rest is None
                        else rp.sky_to_device(sky_rest, self.rdt))
                    log("Pallas coherency kernel enabled"
                        + ("" if sky_rest is None
                           else " (hybrid: shapelet/disk/ring via XLA)"))
                except Exception as e:      # pragma: no cover - hw path
                    log(f"Pallas kernel unavailable ({type(e).__name__}); "
                        "using the XLA path")
        mode = effective_solver_mode(int(cfg.solver_mode), self.n)
        self.base_cfg = sage.SageConfig(
            max_emiter=cfg.max_em_iter, max_iter=cfg.max_iter,
            max_lbfgs=0 if cfg.per_channel_bfgs else cfg.max_lbfgs,
            lbfgs_m=cfg.lbfgs_m, solver_mode=mode, nulow=cfg.robust_nulow,
            nuhigh=cfg.robust_nuhigh, randomize=cfg.randomize,
            linsolv=cfg.linsolv,
            fuse=getattr(cfg, "solve_fuse", "auto"),
            promote=getattr(cfg, "solve_promote", "auto"),
            inflight=max(1, int(getattr(cfg, "cluster_inflight", 1))),
            inner=getattr(cfg, "solver_inner", "chol"),
            kernel=getattr(cfg, "solver_kernel", "xla"),
            jones_mode=getattr(cfg, "jones_mode", "full"),
            dtype_policy=self.dtype_policy,
            # rows are [tilesz, nbase] (io.dataset layout): lets the
            # solvers' normal-equation assembly take the baseline-major
            # aggregation for single-chunk clusters
            nbase=int(meta["nbase"]))
        self.boost = first_tile_boost(self.n)

        # process-wide program-cache key (serve/cache.py): tokens EVERY
        # closure constant the per-pipeline jitted programs capture —
        # the post-precession device sky, shape statics at the BUCKET
        # tilesz, dtype policy, solver flags, and the residual/
        # simulation knobs — so a second job with an equal key shares
        # the first job's warm-compiled wrappers (zero new compiles,
        # asserted via diag/guard) and an unequal key can never reuse a
        # stale closure. The cache may keep a prior pipeline (and its
        # dataset handle) alive through a cached bound method; the LRU
        # bound in serve.cache caps that retention.
        self._ckey = pcache.token(
            [np.asarray(a) for a in self.dsky],
            dict(freq0=meta["freq0"], fdelta=meta["fdelta"],
                 freqs=list(meta["freqs"]), tilesz=self.tilesz_eff,
                 nbase=int(meta["nbase"]), n=self.n),
            self.cidx, self.cmask, sky.cluster_ids, sky.nchunk,
            str(np.dtype(self.rdt)), str(np.dtype(self.sdt)),
            self.dtype_policy, int(self.dobeam), bool(self.use_pallas),
            tuple(self.base_cfg),
            dict(mmse_rho=cfg.mmse_rho, correct=cfg.correct_cluster,
                 phase_only=bool(cfg.phase_only),
                 sim=int(cfg.simulation)))

        # --tile-batch: T>1 solves T intervals as one vmapped program
        # (sagefit_host_tiles) — the utilization lever for small solves.
        # The beam path batches too (only the per-tile gmst track
        # differs between tiles — it becomes a leading axis, VERDICT r5
        # item 7); the sharded path is its own program and per-channel
        # mode re-solves per channel.
        self.tile_batch = max(1, int(getattr(cfg, "tile_batch", 1)))
        self.batch_ok = (self.tile_batch > 1 and not cfg.per_channel_bfgs
                         and not getattr(cfg, "shard_baselines", False))
        if self.tile_batch > 1 and not self.batch_ok:
            log("tile-batch disabled (per-channel/sharded path); "
                "running sequentially")
        self._solve_tiles = (self._build_tiles_solver(self.tile_batch)
                             if self.batch_ok else None)

        self._solve_first = self._build_solver(self.boost)
        self._solve_rest = self._build_solver(1, warm=True)
        # the staged per-tile visibility buffer is DONATED: the residual
        # program writes the subtracted visibilities in place of its
        # input (same [B, F, ..] real shape) instead of allocating a
        # second tile-sized buffer per interval — callers stage x_r
        # fresh from tile.x and only ever read the output back
        self._residual_fn = self._jit_cached(
            "residual",
            lambda: jax.jit(self._residuals, donate_argnums=(1,)))
        self._sim_jit = None       # bound by run_simulation via the
        #                            program cache (keyed, not per-instance)
        self._chan_solver = None
        self._chan_residual_fn = None
        if cfg.per_channel_bfgs:
            self._chan_solver = self._build_chan_solver()
            self._chan_residual_fn = self._build_chan_residual()

    # NOTE on jit boundaries: complex arrays cannot cross host<->device on
    # the axon TPU runtime, so solvers take/return Jones as [.., N, 8]
    # reals and visibilities as stacked [..., 2] real pairs (utils.c2r).

    def _jit_cached(self, kind: str, build, *extra):
        """A jit wrapper shared through the process-wide program cache:
        ``build()`` runs once per (kind, content key, device ordinal,
        extra); every later pipeline with an equal key — another job in
        the same server, or this pipeline rebuilt — reuses the warm
        wrapper instead of silently re-tracing (serve/cache.py). The
        fleet ordinal (serve/fleet.py; 0 outside any device scope, so
        solo keys are unchanged in meaning) keys programs PER DEVICE:
        jax would recompile per device underneath one shared wrapper
        anyway — separate keys make that cost a visible per-device
        cache miss the fleet placer can route around."""
        return pcache.PROGRAMS.get(
            ("prog", kind, self._ckey, pfleet.current_ordinal()) + extra,
            build)

    def _inflight_downgrade(self, log=print) -> None:
        """Divergence guard for --inflight (VERDICT r5 item 6): a
        divergence reset with block-Jacobi groups active is treated as
        evidence of group overcorrection, and the run falls back to the
        reference's strict sequential cluster updates for all remaining
        tiles — the same downgrade philosophy as the LMCUT solver
        fallback (fullbatch_mode.cpp:397). Sticky: groups never re-arm
        within the run. Callers skip it for res_1 == 0 resets (fully
        flagged data says nothing about group overcorrection); residual
        growth and non-finite blowups both count as evidence."""
        if self.base_cfg.inflight <= 1:
            return
        log("inflight downgrade: divergence reset with cluster groups "
            "active; falling back to sequential updates (G=1)")
        self.base_cfg = self.base_cfg._replace(inflight=1)
        self._solve_first = self._build_solver(self.boost)
        self._solve_rest = self._build_solver(1, warm=True)
        if self._solve_tiles is not None:
            self._solve_tiles = self._build_tiles_solver(self.tile_batch)

    def _build_solver(self, emiter_mult: int, warm: bool = False):
        scfg = self.base_cfg._replace(
            max_emiter=self.base_cfg.max_emiter * emiter_mult,
            # warm solves (J0 carried from the previous tile) skip the
            # cold-start inflight width restriction (sage.SageConfig)
            inflight_warm=warm)
        meta = self.ms.meta
        freq0 = meta["freq0"]
        fdelta = meta["fdelta"]
        cidx = jnp.asarray(self.cidx)
        cmask = jnp.asarray(self.cmask)

        if getattr(self.cfg, "shard_baselines", False):
            return self._build_sharded_solver(scfg, meta, freq0, fdelta)

        tslot = jnp.asarray(self.tslot)
        # ordered-subsets partition for solver modes 1/2/3 (P4,
        # clmfit.c:1074); harmless to pass for other modes. Built at
        # the BUCKET tilesz: staged rows are padded to it
        os_info = lm_mod.os_subset_ids(self.tilesz_eff, meta["nbase"])

        if self.use_pallas:
            pg, rest = self._pallas_skies
            coh_fn = self._jit_cached("coh", lambda: jax.jit(
                lambda u, v, w, sta1, sta2, beam: (
                    rp.coherencies_split(pg, rest, u, v, w,
                                         jnp.asarray([freq0], self.rdt),
                                         fdelta)[:, :, 0])))
        else:
            coh_fn = self._jit_cached("coh", lambda: jax.jit(
                lambda u, v, w, sta1, sta2, beam: (
                    rp.coherencies(self.dsky, u, v, w,
                                   jnp.asarray([freq0], self.rdt),
                                   fdelta, beam=beam, dobeam=self.dobeam,
                                   tslot=tslot, sta1=sta1,
                                   sta2=sta2)[:, :, 0])))

        def solve(x8, u, v, w, sta1, sta2, wt, J0_r8, beam, tile_idx=0):
            # host-driven EM: one bounded device execution per cluster
            # solve (the tunneled chip kills single executions over ~60 s)
            coh = coh_fn(u, v, w, sta1, sta2, beam)
            # jitted conversion: eager complex ops are unimplemented on
            # the axon TPU runtime
            J0 = _jones_r2c_j(jnp.asarray(J0_r8, self.rdt))
            # fresh subset draws + cluster permutations per tile
            key = jax.random.fold_in(jax.random.PRNGKey(199), tile_idx)
            J, info = sage.sagefit_host(
                jnp.asarray(x8, self.rdt), coh, sta1, sta2, cidx, cmask,
                J0, self.n, wt, config=scfg, os_id=os_info, key=key)
            return _jones_c2r_j(J), info
        return solve

    def _build_tiles_solver(self, T: int):
        """Batched variant of :meth:`_build_solver` (emiter_mult=1): T
        staged tiles solve as one vmapped program. Per-tile PRNG keys are
        the SAME fold_in(199, tile_idx) stream as the sequential path, so
        each tile's subset draws/permutations match a sequential run —
        only the warm start differs (batch-granular instead of
        tile-granular)."""
        # batches always run after the solo boost tile, so they are
        # warm-started (the cold-start inflight restriction is the solo
        # first solve's job)
        scfg = self.base_cfg._replace(inflight_warm=True)
        meta = self.ms.meta
        freq0 = meta["freq0"]
        fdelta = meta["fdelta"]
        cidx = jnp.asarray(self.cidx)
        cmask = jnp.asarray(self.cmask)
        os_info = lm_mod.os_subset_ids(self.tilesz_eff, meta["nbase"])
        freq = jnp.asarray([freq0], self.rdt)

        tslot = jnp.asarray(self.tslot)

        if self.use_pallas:
            # pallas is never enabled together with the beam (see the
            # probe gating above), so the beam argument is ignored here
            pg, rest = self._pallas_skies

            def coh_one(u1, v1, w1, beam_t, s1, s2):
                return rp.coherencies_split(pg, rest, u1, v1, w1, freq,
                                            fdelta)[:, :, 0]
        else:
            def coh_one(u1, v1, w1, beam_t, s1, s2):
                return rp.coherencies(self.dsky, u1, v1, w1, freq,
                                      fdelta, beam=beam_t,
                                      dobeam=self.dobeam, tslot=tslot,
                                      sta1=s1, sta2=s2)[:, :, 0]

        # per-tile beam: only the gmst time track differs between tiles
        # (stations/elements/pattern are tile-invariant), so the batch
        # carries ONE BeamArrays with a [T, tilesz] gmst and each tile's
        # predict slices its row at trace time
        coh_fn = self._jit_cached("coh_tiles", lambda: jax.jit(
            lambda u, v, w, beamT, s1, s2: jnp.stack(
                [coh_one(u[t], v[t], w[t],
                         (None if beamT is None
                          else beamT._replace(gmst=beamT.gmst[t])), s1, s2)
                 for t in range(T)])), T)

        def solve(x8T, uT, vT, wT, sta1, sta2, wtT, J0_r8T, tile_ids,
                  beamT=None):
            coh = coh_fn(uT, vT, wT, beamT, sta1, sta2)
            keys = jnp.stack([
                jax.random.fold_in(jax.random.PRNGKey(199), int(ti))
                for ti in tile_ids])
            J, info = sage.sagefit_host_tiles(
                jnp.asarray(x8T, self.rdt), coh, sta1, sta2, cidx, cmask,
                _jones_r2c_j(jnp.asarray(J0_r8T, self.rdt)), self.n, wtT,
                config=scfg, os_id=os_info, keys=keys)
            return _jones_c2r_j(J), info
        return solve

    def _build_sharded_solver(self, scfg, meta, freq0, fdelta):
        """--shard-baselines: one subband spanning the whole mesh (P1).

        The predict + SAGE solve runs as ONE program with the row axis
        sharded over a "base" mesh axis and the solutions replicated —
        GSPMD places the all-reduces (parallel.sharded_sagefit). Rows
        pad to the mesh with zero weight; the OS-subset ids and per-tile
        PRNG key ride through so modes 1/2/3 keep the P4 acceleration;
        beam tables replicate while the row-indexed gathers shard."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from sagecal_tpu import parallel

        mesh = parallel.base_mesh()
        ndev = mesh.devices.size
        os_ids_np, os_nsub = lm_mod.os_subset_ids(meta["tilesz"],
                                                  meta["nbase"])
        # row-sharding (+ zero-weight padding) breaks the [tilesz,
        # nbase] period a shard-local normal-equation assembly would
        # assume — disable the baseline-major path here
        scfg = scfg._replace(nbase=0)
        solve_j = parallel.sharded_sagefit(mesh, self.dsky, fdelta,
                                           self.cmask, self.n,
                                           config=scfg, os_nsub=os_nsub,
                                           dobeam=self.dobeam)
        tslot_np = np.asarray(self.tslot)
        cidx_np = np.asarray(self.cidx)
        freq = np.asarray([freq0])
        repl = NamedSharding(mesh, P())

        def solve(x8, u, v, w, sta1, sta2, wt, J0_r8, beam, tile_idx=0):
            B = np.asarray(x8).shape[0]
            arrs, wtp, bpad = parallel.pad_rows(
                (x8, u, v, w, sta1, sta2), wt, B, ndev)
            cidxp = np.concatenate(
                [cidx_np, np.zeros((cidx_np.shape[0], bpad - B),
                                   cidx_np.dtype)], axis=1)
            # padded rows get subset id 0 / timeslot 0; their zero
            # weight already excludes them from every reduction
            osp = np.concatenate(
                [np.asarray(os_ids_np),
                 np.zeros(bpad - B, np.asarray(os_ids_np).dtype)])
            tsp = np.concatenate(
                [tslot_np, np.zeros(bpad - B, tslot_np.dtype)])
            # dtype policy: the [B]-proportional rows (x8, wt) stage in
            # the storage dtype; geometry (u, v, w) keeps the pipeline
            # dtype (the RIME phase needs every f32 bit). Identity when
            # the policy is "f32".
            x8p, geom = arrs[0], arrs[1:]
            args = parallel.shard_rows(
                mesh, np.asarray(x8p, np.dtype(self.sdt)),
                *[np.asarray(a, np.dtype(self.rdt)
                             if np.asarray(a).dtype.kind == "f"
                             else None) for a in geom])
            (cidx_d,) = parallel.shard_rows(mesh, cidxp, row_axis=1)
            (wt_d,) = parallel.shard_rows(
                mesh, np.asarray(wtp, np.dtype(self.sdt)))
            (os_d,) = parallel.shard_rows(mesh, osp)
            (ts_d,) = parallel.shard_rows(mesh, tsp)
            key = jax.random.fold_in(jax.random.PRNGKey(199), tile_idx)
            beam_d = (None if beam is None
                      else jax.device_put(beam, repl))
            J, r0, r1, mnu = solve_j(
                *args, cidx_d, wt_d,
                jax.device_put(jnp.asarray(J0_r8, self.rdt), repl),
                jax.device_put(jnp.asarray(freq, self.rdt), repl),
                os_d, jax.device_put(key, repl), ts_d, beam_d)
            return J, {"res_0": r0, "res_1": r1, "mean_nu": mnu}
        return solve

    def _precess_sources(self, log=print):
        """Apply J2000 -> epoch-of-date precession to the device sky's
        (ra, dec) and the beam pointing (data.cpp:1473 semantics: the
        rotation is evaluated at the first tile's mid-timeslot JD)."""
        import dataclasses
        try:
            t0 = self.ms.read_tile(0)
        except Exception:
            t0 = None
        tj = None if t0 is None else t0.time_jd
        if tj is None:
            return      # placeholder-epoch warning fires in _tile_beam
        jd = float(np.asarray(tj)[len(np.asarray(tj)) // 2])
        pmat = coords.precession_matrix(jd)
        ra_p, dec_p = coords.precess_radec_std(self.dsky.ra, self.dsky.dec,
                                               pmat)
        self.dsky = self.dsky._replace(ra=ra_p, dec=dec_p)
        b_ra, b_dec = coords.precess_radec_std(
            jnp.asarray(self.beam_info.ra0, self.rdt),
            jnp.asarray(self.beam_info.dec0, self.rdt), pmat)
        self.beam_info = dataclasses.replace(
            self.beam_info, ra0=float(b_ra), dec0=float(b_dec))
        self.precessed = True
        log(f"Precessed source/beam coordinates to JD {jd:.5f}")

    def _tile_beam(self, tile):
        """Per-tile device beam tables (times change per tile)."""
        if not self.dobeam:
            return None
        if tile.time_mjd is None and not self._warned_no_times:
            self.log("WARNING: dataset tiles carry no timestamps; beam "
                     "az/el will be evaluated at the J2000 placeholder epoch")
            self._warned_no_times = True
        return bm.beam_to_device(self.beam_info, self.ms.meta["freq0"],
                                 self.rdt, time_jd=tile.time_jd)

    def _correct_idx(self):
        """-k cluster id -> padded-array index (or None)."""
        from sagecal_tpu import skymodel
        return skymodel.correct_cluster_index(
            self.sky, self.cfg.correct_cluster)

    def _residuals(self, J_r8, x_r, u, v, w, sta1, sta2, beam=None,
                   freqs=None, out_dtype=None):
        """Residuals over ``freqs`` (default: all channels; a single
        [1] freq gives the per-channel -b 1 path, fullbatch_mode.cpp:483)."""
        meta = self.ms.meta
        if freqs is None:
            freqs = jnp.asarray(meta["freqs"], self.rdt)
        sub = jnp.asarray(self.sky.subtract_mask())
        res = rr.calculate_residuals_multifreq(
            self.dsky, ne.jones_r2c(J_r8), utils.r2c(x_r), u, v, w, freqs,
            meta["fdelta"] / len(meta["freqs"]), sta1, sta2,
            jnp.asarray(self.cidx), sub, correct_idx=self._correct_idx(),
            rho=self.cfg.mmse_rho,
            beam=beam, dobeam=self.dobeam, tslot=jnp.asarray(self.tslot),
            phase_only=self.cfg.phase_only)
        # storage-dtype writeback emission: the donated x_r slot and
        # this output share shape AND dtype, so the ring keeps working
        # and the d->h readback ships storage bytes (rr doc)
        return rr.residual_writeback(
            res, self.sdt if out_dtype is None else out_dtype)

    def _chan_residual(self, J_r8, x_r, u, v, w, sta1, sta2, freq, beam):
        # the -b 1 channel path assembles its residuals host-side with
        # numpy (no ml_dtypes support), so it keeps the pipeline dtype
        return self._residuals(J_r8, x_r, u, v, w, sta1, sta2, beam,
                               freqs=freq[None], out_dtype=self.rdt)

    def _build_chan_residual(self):
        """All channels' residuals in one program (vmap over channels)."""
        return self._jit_cached("chan_residual", lambda: jax.jit(jax.vmap(
            self._chan_residual,
            in_axes=(0, 0, None, None, None, None, None, 0, None))))

    def _build_chan_solver(self):
        """Per-channel bandpass solve (-b 1, fullbatch_mode.cpp:442-488):
        LBFGS-only joint fit at ONE channel, warm-started from the joint
        solution. All channels are independent (each warm-starts from the
        same joint p, fullbatch_mode.cpp:456 memcpy) so the whole channel
        axis solves as ONE vmapped program instead of the reference's
        sequential per-channel loop."""
        meta = self.ms.meta
        fdelta_chan = meta["fdelta"] / len(meta["freqs"])
        cidx = jnp.asarray(self.cidx)
        cmask = jnp.asarray(self.cmask)
        scfg = self.base_cfg._replace(max_lbfgs=self.cfg.max_lbfgs)

        def solve(x8, wt, freq, u, v, w, sta1, sta2, J0_r8, beam):
            if self.use_pallas:
                pg, rest = self._pallas_skies
                coh = rp.coherencies_split(pg, rest, u, v, w, freq[None],
                                           fdelta_chan,
                                           per_channel_flux=True)[:, :, 0]
            else:
                coh = rp.coherencies(self.dsky, u, v, w, freq[None],
                                     fdelta_chan, per_channel_flux=True,
                                     beam=beam, dobeam=self.dobeam,
                                     tslot=jnp.asarray(self.tslot),
                                     sta1=sta1, sta2=sta2)[:, :, 0]
            J, info = sage.bfgsfit(x8, coh, sta1, sta2, cidx,
                                   ne.jones_r2c(J0_r8), self.n, wt,
                                   config=scfg, nu=self.cfg.robust_nulow)
            return ne.jones_c2r(J), info["res_0"], info["res_1"]

        return self._jit_cached(
            "chan_solver", lambda: jax.jit(jax.vmap(
                solve, in_axes=(0, 0, 0, None, None, None, None, None,
                                None, None))),
            int(self.cfg.max_lbfgs), float(self.cfg.robust_nulow))

    def initial_jones(self) -> np.ndarray:
        M = self.sky.n_clusters
        J0 = np.tile(np.eye(2, dtype=np.complex128),
                     (M, self.kmax, self.n, 1, 1))
        if self.cfg.init_solutions:
            Jq = sol.read_warm_start(self.cfg.init_solutions, self.sky,
                                     self.n)
            if Jq is not None:
                J0 = Jq
        return J0

    # -- warm-start prior store (sagecal_tpu.serve.priors) -----------------

    def _interval_times(self, ti: int) -> np.ndarray:
        """Mid-times (seconds from observation start) of tile ``ti``'s
        ``kmax`` solve intervals — the temporal axis the prior store
        interpolates stored chains on. Clusters with fewer than kmax
        chunks are seeded on the kmax grid anyway (their extra k
        columns are masked out of the solve by ``cmask``)."""
        meta = self.ms.meta
        span = float(meta["tilesz"]) * float(meta["tdelta"])
        return (float(ti)
                + (np.arange(self.kmax) + 0.5) / self.kmax) * span

    def prior_key(self) -> str | None:
        """This run's key in the solution prior store: sky/cluster
        content digest + station count + band center + solver family
        (priors.prior_key). Cached; None = unkeyable (no seeding, no
        banking — never an error)."""
        if not hasattr(self, "_prior_key"):
            self._prior_key = ppriors.prior_key(
                self.cfg.sky_model, self.cfg.cluster_file, self.n,
                self.ms.meta["freq0"],
                ppriors.solver_family(
                    self.cfg.solver_mode,
                    getattr(self.cfg, "jones_mode", "full")))
        return self._prior_key

    def prior_initial_jones(self, start_tile: int = 0):
        """Warm J0 seed [M, kmax, N, 2, 2] interpolated from a banked
        same-key solution, or None (cold start — a miss, a refusal,
        or prior_cache off). An explicit ``-q`` init_solutions file
        always wins: that is the operator's seed, not the cache's."""
        mode = getattr(self.cfg, "prior_cache", "off")
        if not ppriors.reads(mode) or self.cfg.init_solutions:
            return None
        J0, _rho = ppriors.PRIORS.seed(
            self.prior_key(), self._interval_times(start_tile),
            self.ms.meta["freq0"], self.n, self.sky.n_clusters,
            jones_mode=getattr(self.cfg, "jones_mode", "full"))
        return J0

    # -- overlapped execution (sagecal_tpu.sched) --------------------------

    def _prefetch_depth(self, prefetch) -> int:
        """Effective overlap depth: the per-call override, else the run
        config's --prefetch (default 1 = double-buffered)."""
        if prefetch is None:
            prefetch = getattr(self.cfg, "prefetch", 1)
        return max(0, int(prefetch))

    def _tile_source(self, stage_fn, max_tiles, depth, start=0,
                     stream=None):
        """Yield ``(ti, tile, staged, io_wait_s)`` with read + host
        staging running ``depth`` tiles ahead on a background thread
        (depth 0: inline — the synchronous reference path). The io
        wait is the consumer's bubble; the thread's own read+stage
        time is emitted as a ``bg``-tagged "read" phase and its
        wait-for-arrival (pacing or a live transport) as
        ``arrival_wait`` — never folded into io. ``start``: first tile
        to produce (checkpoint resume skips completed tiles); the
        produced payload carries the ABSOLUTE tile id. ``stream``: a
        :class:`sagecal_tpu.stream.TileStream` — production then runs
        OPEN-ENDED (tile count unknown; the transport's EndOfStream is
        the end) and each staged payload carries the tile's arrival
        stamp for the arrival-to-write latency SLO."""
        if stream is not None:
            def produce(_j, _strm=stream):
                i, tile, t_arr = _strm.take()
                stg = stage_fn(i, tile)
                stg["_t_arrival"] = t_arr
                return i, tile, stg

            pf = sched.Prefetcher(produce, None, depth=depth,
                                  arrive=stream.wait_next)
        else:
            n = self.ms.n_tiles
            if max_tiles is not None:
                n = min(n, max_tiles)

            def produce(j):
                i = start + j
                tile = self.ms.read_tile(i)
                return i, tile, stage_fn(i, tile)

            pf = sched.Prefetcher(
                produce, max(0, n - start), depth=depth,
                pace_s=getattr(self.cfg, "tile_arrival_s", 0.0))
        for _j, (ti, tile, stg), wait in pf:
            dtrace.emit("phase", name="io", tile=ti, dur_s=wait)
            yield ti, tile, stg, wait

    def _write_residual_tile(self, ti, tile, res_r, bg=True):
        """Fetch the residual buffer (already copy-to-host-async'd on
        the overlapped path) and write the MS tile. Runs as the
        writer-thread job under overlap (``bg=True``) or inline on the
        synchronous path; the "write" phase covers fetch + disk so the
        sync attribution shows the full data-movement stall."""
        t_write = time.perf_counter()
        with dtrace.phase("write", tile=ti, bg=bg):
            # residual_fetch: the d->h readback chaos seam; this whole
            # method runs as one idempotent writer job (pure fetch +
            # atomic MS write), so the writer retry layer recovers a
            # transient fault here
            faults.inject("residual_fetch", key=ti)
            n_rows = tile.x.shape[0]
            # fetch through float64: numpy-side r2c on ml_dtypes bf16
            # arrays is not supported, and the MS stores complex128
            x = utils.r2c(np.asarray(res_r, np.float64)).astype(
                np.complex128)
            # tile-bucket padding rows (zero weight, never solved on)
            # are sliced off before the MS sees them
            tile.x = x[:n_rows]
            self.ms.write_tile(ti, tile)
        obs.observe("tile_write_seconds", time.perf_counter() - t_write)

    def _run_batched(self, write_residuals, solution_path, max_tiles, log,
                     prefetch=None):
        """--tile-batch>1 fullbatch driver: tile 0 (and every re-armed
        boost tile after a divergence reset) solves solo, then groups of
        T tiles solve as ONE vmapped program (sagefit_host_tiles); the
        stream tail runs solo. Semantics vs the sequential driver: each
        tile in a group warm-starts from the solution carried into the
        group (batch-granular warm start) — everything else (PRNG
        streams, residual math, divergence resets, solution writing)
        matches tile for tile."""
        cfg, ms, sky = self.cfg, self.ms, self.sky
        meta = ms.meta
        from sagecal_tpu.solvers import robust as rb
        T = self.tile_batch
        depth = self._prefetch_depth(prefetch)
        pinit = self.initial_jones()
        writer = None
        if solution_path:
            writer = sol.SolutionWriter(
                solution_path, meta["freq0"], meta["fdelta"],
                meta["tilesz"] * meta["tdelta"] / 60.0, self.n,
                sky.n_clusters, sky.n_eff_clusters)
        history = []
        state = {"J": pinit.copy(), "first": True, "res_prev": None}
        pending = []
        # donated-staging ring: up to T pending + depth prefetched +
        # in-flight slots hold a staged residual input concurrently
        ring = sched.DonatedRing(T + depth + 2)
        aw = sched.AsyncWriter(enabled=depth > 0)

        def stage(ti, tile):
            t_stage = time.perf_counter()
            u = jnp.asarray(tile.u, self.rdt)
            v = jnp.asarray(tile.v, self.rdt)
            w = jnp.asarray(tile.w, self.rdt)
            x8_np, rowflags, _good = tile.solve_input(uvtaper_m=cfg.uvtaper)
            # staged in the dtype-policy storage dtype: the prefetcher
            # and the solve both ship sdt bytes (sdt == rdt at "f32")
            x8 = jnp.asarray(x8_np, self.sdt)
            flags = rp.uvcut_flags(jnp.asarray(rowflags, jnp.int32), u, v,
                                   jnp.asarray(tile.freqs, self.rdt),
                                   cfg.uvmin, cfg.uvmax)
            if cfg.whiten:
                x8 = rb.whiten_data(x8, u, v, meta["freq0"])
            out = dict(ti=ti, tile=tile, u=u, v=v, w=w, x8=x8,
                       wt=lm_mod.make_weights(flags, self.sdt),
                       sta1=jnp.asarray(tile.sta1),
                       sta2=jnp.asarray(tile.sta2),
                       # staged once: solve + residual write reuse it
                       beam=self._tile_beam(tile), bubble=0.0)
            if write_residuals:
                # the residual program DONATES its staged visibility
                # input; the ring keeps overlapped staging from ever
                # aliasing an in-flight donated buffer
                ring.stage(ti, jnp.asarray(utils.c2r(tile.x), self.sdt))
            dur = time.perf_counter() - t_stage
            dtrace.emit("phase", name="stage", tile=ti,
                        dur_s=dur, bg=depth > 0)
            obs.observe("tile_stage_seconds", dur)
            return out

        def post(stg, res_0, res_1, mean_nu, Jnew, minutes):
            ti, tile = stg["ti"], stg["tile"]
            if res_1 == 0.0 or not np.isfinite(res_1) or (
                    state["res_prev"] is not None
                    and res_1 > RES_RATIO * state["res_prev"]):
                log(f"tile {ti}: Resetting Solution")
                if res_1 != 0.0:    # zero = flagged data, not divergence
                    self._inflight_downgrade(log)
                state["J"] = pinit.copy()
                state["first"] = True
                state["res_prev"] = res_1 if np.isfinite(res_1) else None
            else:
                state["J"] = Jnew
                state["res_prev"] = (res_1 if state["res_prev"] is None
                                     else min(state["res_prev"], res_1))
            if writer:
                stg["bubble"] += aw.submit(
                    writer.write_interval,
                    state["J"] if state["first"] else Jnew, sky.nchunk)
            if write_residuals:
                t_res = time.perf_counter()
                res_r = self._residual_fn(
                    jnp.asarray(utils.jones_c2r_np(
                        state["J"] if state["first"] else Jnew), self.rdt),
                    ring.take(ti),
                    stg["u"], stg["v"], stg["w"], stg["sta1"], stg["sta2"],
                    stg["beam"])
                dtrace.emit("phase", name="residual", tile=ti,
                            dur_s=time.perf_counter() - t_res)
                if depth > 0:
                    # start the non-blocking device->host copy, hand
                    # fetch + MS write to the ordered writer thread
                    sched.start_host_copy(res_r)
                # depth 0 runs the same job inline through submit —
                # one path, so the transient-retry layer covers both
                stg["bubble"] += aw.submit(
                    self._write_residual_tile, ti, tile, res_r,
                    bg=depth > 0)
            log(f"Timeslot: {ti} Residual: initial={res_0:.6g}, "
                f"final={res_1:.6g}, Time spent={minutes:.3g} minutes, "
                f"nu={mean_nu:.2f}")
            history.append({"tile": ti, "res_0": res_0, "res_1": res_1,
                            "mean_nu": mean_nu, "minutes": minutes})
            _emit_tile_record(ti, res_0, res_1, mean_nu, None, minutes,
                              bubble_s=stg["bubble"], overlap=depth)

        def solve_solo(stg, boosted):
            t0 = time.time()
            solver = self._solve_first if boosted else self._solve_rest
            J_r8 = jnp.asarray(utils.jones_c2r_np(state["J"]), self.rdt)
            Jd_r8, info = solver(stg["x8"], stg["u"], stg["v"], stg["w"],
                                 stg["sta1"], stg["sta2"], stg["wt"],
                                 J_r8, stg["beam"], tile_idx=stg["ti"])
            dtrace.emit("phase", name="solve", tile=stg["ti"],
                        dur_s=time.time() - t0)
            obs.observe("tile_solve_seconds", time.time() - t0)
            state["first"] = False
            post(stg, float(info["res_0"]), float(info["res_1"]),
                 float(info["mean_nu"]),
                 utils.jones_r2c_np(np.asarray(Jd_r8)),
                 (time.time() - t0) / 60.0)

        def flush(group):
            if not group:
                return
            if len(group) < T:
                for stg in group:
                    solve_solo(stg, boosted=False)
                return
            t0 = time.time()
            J0 = np.broadcast_to(
                utils.jones_c2r_np(state["J"]),
                (T,) + utils.jones_c2r_np(state["J"]).shape).copy()
            beamT = None
            if self.dobeam:
                beamT = group[0]["beam"]._replace(
                    gmst=jnp.stack([g["beam"].gmst for g in group]))
            Jd, info = self._solve_tiles(
                jnp.stack([g["x8"] for g in group]),
                jnp.stack([g["u"] for g in group]),
                jnp.stack([g["v"] for g in group]),
                jnp.stack([g["w"] for g in group]),
                group[0]["sta1"], group[0]["sta2"],
                jnp.stack([g["wt"] for g in group]),
                J0, [g["ti"] for g in group], beamT=beamT)
            Jd = np.asarray(Jd)
            r0 = np.asarray(info["res_0"])
            r1 = np.asarray(info["res_1"])
            mnu = np.asarray(info["mean_nu"])
            dtrace.emit("phase", name="solve", tiles=T,
                        dur_s=time.time() - t0)
            if obs.active():
                # one amortized observation PER TILE, so the histogram
                # count stays equal to tiles_solved_total under
                # --tile-batch too
                dur = (time.time() - t0) / T
                for _ in range(T):
                    obs.observe("tile_solve_seconds", dur)
            minutes = (time.time() - t0) / 60.0 / T
            for t, stg in enumerate(group):
                post(stg, float(r0[t]), float(r1[t]), float(mnu[t]),
                     utils.jones_r2c_np(Jd[t]), minutes)

        try:
            for ti, tile, stg, io_wait in self._tile_source(
                    stage, max_tiles, depth):
                aw.check()      # writer failure -> fail at the boundary
                stg["bubble"] += io_wait
                if state["first"]:
                    solve_solo(stg, boosted=True)
                    continue
                pending.append(stg)
                if len(pending) == T:
                    flush(pending)
                    pending = []
        finally:
            try:
                flush(pending)
            finally:
                aw.close()
                if writer:
                    writer.close()
        return history

    def stepper(self, write_residuals: bool = True, solution_path=None,
                max_tiles=None, log=print, prefetch=None,
                trace_ctx=None, on_diverge: str = "reset",
                open_ended: bool = False) -> "TileStepper":
        """The sequential driver as a resumable per-tile unit: the
        serve scheduler owns ``stage``/``step``/``close`` and may
        interleave many jobs' tiles through one device while each
        job's warm-start/PRNG chain stays sequential inside its own
        :class:`TileStepper`. ``on_diverge``: the divergence policy —
        "reset" (the reference's solution reset) or "quarantine" (keep
        the last-good chain, flag the tile; serve jobs select it per
        submission)."""
        return TileStepper(self, write_residuals=write_residuals,
                           solution_path=solution_path,
                           max_tiles=max_tiles, log=log,
                           depth=self._prefetch_depth(prefetch),
                           trace_ctx=trace_ctx, on_diverge=on_diverge,
                           open_ended=open_ended)

    def run(self, write_residuals: bool = True, solution_path=None,
            max_tiles=None, log=print, prefetch=None, stream=None):
        """``prefetch``: overlap depth override (None = cfg.prefetch;
        0 = the synchronous reference loop). Outputs are bit-identical
        across depths — only data movement overlaps; the warm-start
        solve chain stays sequential (tests/test_overlap.py).
        ``stream``: a live :class:`sagecal_tpu.stream.TileStream` —
        tiles come from the transport (open-ended, arrival-stamped)
        and each one is checked against the per-tile deadline at step
        entry (MIGRATION.md "Streaming mode")."""
        if stream is not None:
            return self._run_stream(stream, write_residuals,
                                    solution_path, log, prefetch)
        if getattr(self, "batch_ok", False):
            if getattr(self.cfg, "resume", False):
                # the batched driver's warm start is batch-granular;
                # a tile-granular checkpoint cannot reproduce it
                log("resume: unsupported on the --tile-batch driver; "
                    "starting fresh")
            return self._run_batched(write_residuals, solution_path,
                                     max_tiles, log, prefetch)
        depth = self._prefetch_depth(prefetch)
        st = self.stepper(write_residuals, solution_path, max_tiles,
                          log, prefetch=depth)
        # --profile: capture an XLA/device timeline of the FIRST solve
        # interval (SURVEY.md section 5 tracing — the reference has only
        # wall-clock prints; a jax.profiler trace is the superset).
        # Bounded to one tile so trace size stays sane.
        prof_dir = getattr(self.cfg, "profile_dir", None)
        prof_live = False
        if prof_dir:
            import jax.profiler
            jax.profiler.start_trace(prof_dir)
            prof_live = True
            log(f"profiling first solve interval -> {prof_dir}")
        try:
            for ti, tile, stg, io_wait in self._tile_source(
                    st.stage, max_tiles, depth, start=st.start_tile):
                st.step(ti, tile, stg, io_wait)
                if prof_live:
                    import jax.profiler
                    jax.profiler.stop_trace()
                    prof_live = False
                    log(f"profile trace written to {prof_dir}")
        finally:
            try:
                st.close()
            finally:
                if prof_live:   # abnormal exit or 0-tile run:
                    import jax.profiler
                    jax.profiler.stop_trace()  # close the trace
        return st.history

    def _run_stream(self, stream, write_residuals=True,
                    solution_path=None, log=print, prefetch=None):
        """Direct (non-serve) streaming driver: open-ended stepping
        over a live :class:`TileStream`, with the per-tile deadline /
        lateness policy applied at each step entry. The serve
        scheduler runs the same seam through poll(); this path is the
        single-job reference (and the bit-identity audit target: with
        no late degradations the outputs match a batch run of the same
        tiles exactly)."""
        depth = self._prefetch_depth(prefetch)
        st = self.stepper(write_residuals, solution_path, None, log,
                          prefetch=depth, open_ended=True)
        try:
            for ti, tile, stg, io_wait in self._tile_source(
                    st.stage, None, depth, stream=stream):
                _late, degrade = stream_tile_late(self.cfg, ti, stg)
                st.step(ti, tile, stg, io_wait, degrade=degrade)
        finally:
            try:
                st.close()
            finally:
                stream.close()
        return st.history

    def run_simulation(self, log=print):
        """Simulation modes -a 1/2/3 (fullbatch_mode.cpp:524-578)."""
        cfg, ms, sky = self.cfg, self.ms, self.sky
        meta = ms.meta
        J = None
        blocks_iter = None
        ignore_mask = None
        if cfg.solutions_file:
            _, blocks = sol.read_solutions(cfg.solutions_file, sky.nchunk)
            blocks_iter = blocks
            if cfg.ignore_clusters_file:
                ignore = skymodel.read_ignore_list(cfg.ignore_clusters_file)
                ignore_mask = np.array(
                    [int(cid) not in ignore for cid in sky.cluster_ids])

        def sim_fn(x_r, u, v, w, sta1, sta2, J_r8, beam):
            J = ne.jones_r2c(J_r8) if J_r8 is not None else None
            out = rr.simulate_visibilities(
                self.dsky, utils.r2c(x_r), u, v, w,
                jnp.asarray(meta["freqs"], self.rdt),
                meta["fdelta"] / len(meta["freqs"]), sta1, sta2,
                mode=int(cfg.simulation), J=J,
                chunk_idx=jnp.asarray(self.cidx), ignore_mask=ignore_mask,
                beam=beam, dobeam=self.dobeam,
                tslot=jnp.asarray(self.tslot))
            return utils.c2r(out)

        # keyed through the process-wide program cache (serve/cache.py)
        # instead of the old per-instance lazy attribute: a second job
        # in the same process used to re-trace every tile shape, and a
        # REUSED pipeline could serve a stale ignore_mask closure — the
        # key tokens the sim mode and the ignore mask (the content key
        # already covers sky/shape/dtype), so neither can happen
        self._sim_jit = self._jit_cached(
            "sim", lambda: jax.jit(sim_fn),
            pcache.token(ignore_mask, int(cfg.simulation)))
        sim_jit = self._sim_jit
        for ti, tile in ms.tiles():
            J_r8 = None
            if blocks_iter:
                J_r8 = jnp.asarray(utils.jones_c2r_np(
                    blocks_iter[min(ti, len(blocks_iter) - 1)]), self.rdt)
            out_r = sim_jit(
                jnp.asarray(utils.c2r(tile.x), self.rdt),
                jnp.asarray(tile.u, self.rdt), jnp.asarray(tile.v, self.rdt),
                jnp.asarray(tile.w, self.rdt),
                jnp.asarray(tile.sta1), jnp.asarray(tile.sta2), J_r8,
                self._tile_beam(tile))
            tile.x = utils.r2c(np.asarray(out_r)).astype(np.complex128)
            ms.write_tile(ti, tile)
            log(f"Timeslot: {ti} simulated (mode={int(cfg.simulation)})")


def stream_tile_late(cfg, ti, stg, key=None):
    """Per-tile deadline check at STEP ENTRY (streaming jobs): a tile
    whose arrival-to-now age already exceeds ``tile_deadline_s`` — or
    that the ``tile_late`` chaos point forces late — is counted
    (``stream_tiles_late_total``) and, under ``late_policy="degrade"``,
    degraded to the last-good-Jones writeback instead of solved. A
    late tile NEVER stalls the stream. Returns ``(late, degrade)``.
    Degradation is unsupported under per-channel BFGS (its residual
    path re-solves; there is no staged last-good writeback), so that
    combination counts only."""
    t_arr = stg.get("_t_arrival")
    ddl = float(getattr(cfg, "tile_deadline_s", 0.0) or 0.0)
    late = faults.fires("tile_late", key=ti if key is None else key)
    if not late and ddl > 0.0 and t_arr is not None:
        late = (time.monotonic() - t_arr) > ddl
    if not late:
        return False, False
    obs.inc("stream_tiles_late_total")
    degrade = (getattr(cfg, "late_policy", "degrade") == "degrade"
               and not cfg.per_channel_bfgs)
    return True, degrade


class TileStepper:
    """One job's resumable per-tile execution unit (sequential driver).

    The serve scheduler's contract (serve/scheduler.py): ``stage(ti,
    tile)`` may run on a background reader thread; ``step(ti, tile,
    staged, io_wait)`` runs on the device-owner thread, strictly in
    tile order *within this job*; ``close()`` flushes the job's
    ordered writer and solution file. All mutable solve state (the
    warm-start Jones chain, divergence-reset bookkeeping, the donated
    staging ring, the per-job AsyncWriter) lives HERE, so interleaving
    tiles from many jobs through one device changes nothing about any
    single job's chain — per-job outputs are bit-identical to a solo
    ``FullBatchPipeline.run`` by construction (and by gate,
    tests/test_serve.py).
    """

    def __init__(self, pipe: "FullBatchPipeline", write_residuals=True,
                 solution_path=None, max_tiles=None, log=print,
                 depth: int = 0, trace_ctx=None,
                 on_diverge: str = "reset", open_ended: bool = False):
        if on_diverge not in ("reset", "quarantine"):
            raise ValueError(f"on_diverge {on_diverge!r}: "
                             "expected 'reset' or 'quarantine'")
        self.p = pipe
        self.log = log
        self.depth = int(depth)
        self.write_residuals = write_residuals
        self.on_diverge = on_diverge
        ms, sky = pipe.ms, pipe.sky
        meta = ms.meta
        self.n_tiles = ms.n_tiles
        if max_tiles:
            self.n_tiles = min(self.n_tiles, int(max_tiles))
        # open-ended (streaming) mode: the tile count is NOT known at
        # start — the transport's EndOfStream is the end, progress is
        # "tiles so far", and checkpoint/resume is disabled: a live
        # stream cannot deterministically re-read its past, so the
        # recovery story is the lateness policy, never a rewind
        # (MIGRATION.md "Streaming mode")
        self.open_ended = bool(open_ended)
        if self.open_ended:
            self.n_tiles = None
        # tile-boundary checkpoint/resume (MIGRATION.md "Fault
        # tolerance"): the sidecar lives next to the solutions file —
        # no solutions file, no checkpoint. The identity meta refuses
        # resuming against a different dataset/sky/solver shape.
        self._ckpt_meta = dict(
            n_tiles=-1 if self.n_tiles is None else int(self.n_tiles),
            n_stations=int(pipe.n),
            n_clusters=int(sky.n_clusters), kmax=int(pipe.kmax),
            tilesz=int(meta["tilesz"]))
        self.ckpt_path = (sol.checkpoint_path(solution_path)
                          if solution_path and not self.open_ended
                          else None)
        ck = None
        if getattr(pipe.cfg, "resume", False) and self.open_ended:
            log("resume: not applicable to a live stream; ignoring")
        elif getattr(pipe.cfg, "resume", False):
            if self.ckpt_path is None:
                log("resume: no solutions file -> no checkpoint; "
                    "starting fresh")
            else:
                ck = sol.load_checkpoint(self.ckpt_path,
                                         expect_meta=self._ckpt_meta)
                if ck is None:
                    log("resume: no checkpoint found; starting fresh")
        self.writer = None
        if solution_path:
            if ck is not None:
                # a kill can land between a solution write and its
                # checkpoint: truncate the file back to the byte
                # watermark of the last CHECKPOINTED interval, then
                # append — the final file is byte-identical to an
                # uninterrupted run's
                size = os.path.getsize(solution_path)
                if size < ck["sol_bytes"]:
                    raise ValueError(
                        f"resume: {solution_path!r} is shorter "
                        f"({size} B) than its checkpoint watermark "
                        f"({ck['sol_bytes']} B); refusing to resume "
                        "from inconsistent state")
                with open(solution_path, "r+") as f:
                    f.truncate(ck["sol_bytes"])
                self.writer = sol.SolutionWriter.open_resume(
                    solution_path, pipe.n)
            else:
                self.writer = sol.SolutionWriter(
                    solution_path, meta["freq0"], meta["fdelta"],
                    meta["tilesz"] * meta["tdelta"] / 60.0, pipe.n,
                    sky.n_clusters, sky.n_eff_clusters)
        self.pinit = pipe.initial_jones()
        self.J = self.pinit.copy()
        self.first = True
        self.res_prev = None
        self.start_tile = 0
        # warm-start prior seed (serve/priors.py): a banked same-key
        # solution replaces the cold identity start and enters the
        # chain as WARM state (first=False — the boosted cold solver
        # exists for identity starts, solvers/sage.py inflight_warm).
        # pinit stays the cold identity: a divergence reset still
        # recovers to the reference start + re-armed boost, so a bad
        # seed costs one reset, never the run. A checkpoint restore
        # (below) overrides the seed — the checkpointed chain IS the
        # job's own state. Under readwrite the post-solve chain is
        # accumulated per tile and banked at a clean close.
        self._prior_mode = getattr(pipe.cfg, "prior_cache", "off")
        self._prior_banked: list = []
        self._prior_res2 = 0.0          # sum |written residual|^2
        self._prior_res_tiles = 0       # over this many banked tiles
        if ck is None:
            Jp = pipe.prior_initial_jones(self.start_tile)
            if Jp is not None:
                self.J = Jp
                self.first = False
                log("prior-cache: J0 seeded from the solution prior "
                    "store (cold identity kept as the divergence-"
                    "reset target)")
        if ck is not None:
            # restore the EXACT chain state at the watermark: the
            # warm-start Jones (full precision — the text file is
            # lossy), the boost/reset flag, the divergence watermark,
            # and a sticky inflight downgrade
            self.start_tile = ck["tile"] + 1
            self.J = ck["J"]
            self.first = ck["first"]
            self.res_prev = ck["res_prev"]
            if ck["inflight"] < pipe.base_cfg.inflight:
                pipe._inflight_downgrade(log)
            log(f"resume: checkpoint at tile {ck['tile']}; skipping "
                f"{self.start_tile}/{self.n_tiles} completed tiles")
        self._last_tile = self.start_tile - 1
        self.history = []
        # donated-staging ring + ordered writer thread (sched): under
        # overlap the next tile reads + stages on a background thread
        # while this one solves, and residual/solution writes drain on
        # the writer thread — strictly in tile order, failures
        # re-raised at the next tile boundary (AsyncWriter.check in
        # step(); per-job, so one job's write failure never touches a
        # neighbour's writer)
        self.ring = sched.DonatedRing(self.depth + 2)
        # trace_ctx: zero-arg diag-scope factory so the writer thread's
        # emits route to the owning job's tracer (serve scheduler)
        self.aw = sched.AsyncWriter(enabled=self.depth > 0,
                                    context=trace_ctx)
        self.stage_xr = write_residuals and not pipe.cfg.per_channel_bfgs

    # -- reader-thread half -------------------------------------------------

    def stage(self, ti, tile):
        p = self.p
        cfg, meta = p.cfg, p.ms.meta
        t_stage = time.perf_counter()
        pad = p.pad_rows
        u_np, v_np, w_np = tile.u, tile.v, tile.w
        sta1_np, sta2_np = tile.sta1, tile.sta2
        # shared staging decision (VisTile.solve_input): native
        # per-channel-flag packing when applicable, plain mean else;
        # stored uv-cut rows survive either way
        x8_np, rowflags, _good = tile.solve_input(uvtaper_m=cfg.uvtaper)
        if pad:
            # tile-bucket padding (serve/cache.py): geometry rows
            # repeat real rows (finite uvw, in-range stations), data
            # rows are zero, and the row flag 1 gives them ZERO weight
            # — they enter no reduction, exactly like the sharded
            # path's mesh padding
            u_np = pcache.pad_rows_repeat(u_np, pad)
            v_np = pcache.pad_rows_repeat(v_np, pad)
            w_np = pcache.pad_rows_repeat(w_np, pad)
            sta1_np = pcache.pad_rows_repeat(sta1_np, pad)
            sta2_np = pcache.pad_rows_repeat(sta2_np, pad)
            x8_np = pcache.pad_rows_zero(x8_np, pad)
            rowflags = np.concatenate(
                [rowflags, np.ones(pad, np.asarray(rowflags).dtype)])
        u = jnp.asarray(u_np, p.rdt)
        v = jnp.asarray(v_np, p.rdt)
        w = jnp.asarray(w_np, p.rdt)
        # dtype-policy storage staging (see the batched driver)
        x8 = jnp.asarray(x8_np, p.sdt)
        flags = rp.uvcut_flags(jnp.asarray(rowflags, jnp.int32), u, v,
                               jnp.asarray(tile.freqs, p.rdt),
                               cfg.uvmin, cfg.uvmax)
        if cfg.whiten:
            # -W: uv-density whitening of the solve input only
            # (fullbatch_mode.cpp applies whiten_data to the averaged x)
            from sagecal_tpu.solvers import robust as rb
            x8 = rb.whiten_data(x8, u, v, meta["freq0"])
        # beam_stage: the beam-table staging chaos seam; it fires
        # BEFORE the ring stages this tile's residual input below, so
        # the reader-thread retry can safely re-run the whole stage
        faults.inject("beam_stage", key=ti)
        stg = dict(u=u, v=v, w=w, x8=x8, flags=flags,
                   wt=lm_mod.make_weights(flags, p.sdt),
                   sta1=jnp.asarray(sta1_np),
                   sta2=jnp.asarray(sta2_np),
                   beam=p._tile_beam(tile))
        if self.stage_xr:
            # residual input staged ahead; DONATED to the residual
            # program (ring: no read-after-donate, no aliasing)
            x_r = tile.x if not pad else pcache.pad_rows_zero(tile.x, pad)
            self.ring.stage(ti, jnp.asarray(utils.c2r(x_r), p.sdt))
        dur = time.perf_counter() - t_stage
        dtrace.emit("phase", name="stage", tile=ti,
                    dur_s=dur, bg=self.depth > 0)
        obs.observe("tile_stage_seconds", dur)
        return stg

    # -- device-owner half --------------------------------------------------

    def step(self, ti, tile, stg, io_wait=0.0, degrade=False):
        p = self.p
        cfg, ms, sky, meta = p.cfg, p.ms, p.sky, p.ms.meta
        log = self.log
        self.aw.check()  # async write failure -> fail at the boundary
        bubble = io_wait
        t0 = time.time()
        # streaming: the transport stamped this tile's arrival; the
        # SLO observation (arrival -> residual durably written) is
        # submitted to the ordered writer AFTER the residual write
        t_arr = stg.pop("_t_arrival", None)
        u, v, w = stg["u"], stg["v"], stg["w"]
        sta1, sta2 = stg["sta1"], stg["sta2"]
        x8, flags, wt = stg["x8"], stg["flags"], stg["wt"]
        tile_beam = stg["beam"]

        degraded = bool(degrade) and not cfg.per_channel_bfgs
        quarantined = False
        if degraded:
            # late-tile degradation (stream_tile_late): the tile
            # missed its per-tile deadline, so its solve is SKIPPED
            # and its solutions/residual come from the LAST-GOOD
            # Jones — the quarantine writeback, triggered by the
            # arrival clock instead of divergence. Bounded staleness
            # for bounded latency; the chain, divergence watermark
            # and boost state stay untouched, exactly as quarantine.
            res_0 = res_1 = mean_nu = float("nan")
            info = None
            log(f"tile {ti}: Late (deadline exceeded; writing "
                "last-good-Jones residual)")
            obs.inc("stream_tiles_degraded_total")
            dtrace.emit("degraded", tile=ti)
        else:
            solver = p._solve_first if self.first else p._solve_rest
            J_prev = self.J          # the last-good chain (quarantine)
            J_r8 = jnp.asarray(utils.jones_c2r_np(self.J), p.rdt)
            t_solve = time.perf_counter()
            Jd_r8, info = solver(x8, u, v, w, sta1, sta2, wt, J_r8,
                                 tile_beam, tile_idx=ti)
            self.first = False
            res_0 = float(info["res_0"])
            res_1 = float(info["res_1"])
            mean_nu = float(info["mean_nu"])
            self.J = utils.jones_r2c_np(np.asarray(Jd_r8))
            dtrace.emit("phase", name="solve", tile=ti,
                        dur_s=time.perf_counter() - t_solve)
            obs.observe("tile_solve_seconds",
                        time.perf_counter() - t_solve)
        # solve_nan: the poisoned-tile chaos seam (a NaN/nonfinite
        # residual drives the divergence policy below)
        if not degraded and faults.active() \
                and faults.fires("solve_nan", key=ti):
            res_1 = float("nan")

        # divergence handling (fullbatch_mode.cpp:605-621): res_1 of
        # exactly 0.0 means fully flagged data and always takes the
        # reference reset; a genuinely divergent solve takes the
        # configured policy. A degraded tile never enters it — its
        # (skipped) solve produced nothing to judge.
        diverged = not degraded and (
                res_1 == 0.0 or not np.isfinite(res_1) or (
                    self.res_prev is not None
                    and res_1 > RES_RATIO * self.res_prev))
        if degraded:
            pass
        elif diverged and res_1 != 0.0 and self.on_diverge == "quarantine":
            # quarantine: the poisoned solve never enters the chain —
            # this tile's solutions/residuals come from the LAST-GOOD
            # Jones, the divergence watermark and boost state stay
            # untouched, and the tile is flagged in the diag trace
            # instead of writing poisoned residuals
            quarantined = True
            log(f"tile {ti}: Quarantined (divergent solve "
                f"res_1={res_1:.6g}; continuing from last-good "
                "solutions)")
            self.J = J_prev
            obs.inc("tiles_quarantined_total")
            dtrace.emit("quarantine", tile=ti, res_1=res_1)
        elif diverged:
            log(f"tile {ti}: Resetting Solution")
            if res_1 != 0.0:   # zero = flagged data, not divergence
                p._inflight_downgrade(log)
            self.J = self.pinit.copy()
            self.first = True
            self.res_prev = res_1 if np.isfinite(res_1) else None
        else:
            self.res_prev = (res_1 if self.res_prev is None
                             else min(self.res_prev, res_1))
        if ppriors.writes(self._prior_mode) and not degraded \
                and not quarantined and not diverged:
            # prior-store accumulation: only chain states that the
            # divergence policy accepted — a reset/quarantined tile's
            # J must never be banked as a seed for the next job
            self._prior_banked.append((ti, self.J.copy()))

        if cfg.per_channel_bfgs:
            bubble += self._step_per_channel(ti, tile, stg, info)
        else:
            if self.writer:
                bubble += self.aw.submit(self.writer.write_interval,
                                         self.J, sky.nchunk)

            if self.write_residuals:
                t_res = time.perf_counter()
                res_r = p._residual_fn(
                    jnp.asarray(utils.jones_c2r_np(self.J), p.rdt),
                    self.ring.take(ti),
                    u, v, w, sta1, sta2, tile_beam)
                dtrace.emit("phase", name="residual", tile=ti,
                            dur_s=time.perf_counter() - t_res)
                if self.depth > 0:
                    # non-blocking d->h copy now; fetch + MS
                    # write on the ordered writer thread
                    sched.start_host_copy(res_r)
                # depth 0 runs the same job inline through submit —
                # one path, so the transient-retry layer covers both
                bubble += self.aw.submit(
                    p._write_residual_tile, ti, tile, res_r,
                    bg=self.depth > 0)
                if ppriors.writes(self._prior_mode) and not degraded \
                        and not quarantined and not diverged:
                    # banked-chain quality rides the same ordered
                    # queue: the UNWEIGHTED norm of the residual this
                    # job writes. The solver's robust res_1 is the
                    # wrong figure here — nu re-weighting IMPROVES it
                    # while the written residual drifts, which is
                    # exactly the degradation the store must refuse
                    bubble += self.aw.submit(
                        self._accum_prior_quality, res_r,
                        tile.x.shape[0])

        if t_arr is not None:
            # the streaming SLO: arrival -> residual durably written.
            # Submitted to the SAME ordered writer queue immediately
            # after this tile's writes, so the stamp is taken only
            # once they landed (depth 0 runs it inline right here)
            self.aw.submit(self._observe_stream_latency, ti, t_arr)

        if self.writer and self.ckpt_path:
            # checkpoint this tile boundary. Submitted to the SAME
            # ordered writer queue AFTER the tile's solution/residual
            # writes: the watermark can only ever name tiles whose
            # outputs durably landed (a failed write skips every later
            # job, checkpoint included — AsyncWriter fail-stop)
            bubble += self.aw.submit(
                self._save_checkpoint,
                dict(tile=ti, J=self.J.copy(), first=self.first,
                     res_prev=self.res_prev,
                     inflight=int(p.base_cfg.inflight)))

        self._last_tile = ti
        dt = (time.time() - t0) / 60.0
        if not degraded:
            log(f"Timeslot: {ti} Residual: initial={res_0:.6g}, "
                f"final={res_1:.6g}, Time spent={dt:.3g} minutes, "
                f"nu={mean_nu:.2f}")
        rec = {"tile": ti, "res_0": res_0, "res_1": res_1,
               "mean_nu": mean_nu, "minutes": dt}
        if isinstance(info, dict) and "solver_iters" in info:
            # executed inner-solver trips — the sweeps-to-convergence
            # signal the serve layer aggregates per job (loadgen
            # replay rows; the warm-vs-cold bench). The solve already
            # synced on res_0/res_1, so this fetch adds no wait.
            rec["solver_iters"] = int(
                np.asarray(info["solver_iters"]).sum())
        if quarantined:
            rec["quarantined"] = True
        if degraded:
            rec["degraded"] = True
        self.history.append(rec)
        _emit_tile_record(ti, res_0, res_1, mean_nu, info, dt,
                          bubble_s=bubble, overlap=self.depth)
        return rec

    def _observe_stream_latency(self, ti, t_arr):
        """Writer-queue job: the per-tile arrival-to-write latency
        observation (TILE_LAT_BUCKETS ladder — declared at stream
        open). Runs strictly after the tile's residual write by
        AsyncWriter ordering."""
        lat = time.monotonic() - t_arr
        obs.observe("stream_tile_latency_seconds", lat)
        dtrace.emit("stream_latency", tile=ti, latency_s=lat)

    def _accum_prior_quality(self, res_r, n_rows) -> None:
        """Writer-queue job: fold one banked tile's written-residual
        power into the prior-quality accumulator. Runs right after
        the tile's residual write on the same ordered queue, so the
        buffer is already host-side; bucket padding rows (never
        solved on) are sliced off like the MS write does."""
        r = np.asarray(res_r, np.float64)[:n_rows]
        self._prior_res2 += float(np.sum(np.square(r)))
        self._prior_res_tiles += 1

    def _bank_priors(self) -> None:
        """Writer-queue job: bank the completed chain in the solution
        prior store (close() submits it only on a clean completion).
        Best-effort — a store refusal logs and moves on; a finished
        job must never fail on its own write-back."""
        p = self.p
        try:
            tis = [t for t, _ in self._prior_banked]
            Js = np.stack([J for _, J in self._prior_banked])
            T, M, K, N = Js.shape[:4]
            times = np.concatenate(
                [p._interval_times(int(t)) for t in tis])
            # [T, M, K, N, 2, 2] -> [1 band, T*K intervals, M, N, 2, 2]
            Jt = np.transpose(Js, (0, 2, 1, 3, 4, 5)).reshape(
                1, T * K, M, N, 2, 2)
            # quality = mean written-residual power per banked tile
            # (accumulated by _accum_prior_quality on this same
            # ordered queue, so every tile has landed by now): the
            # store's refuse-to-degrade guard — a warm repeat whose
            # chain fits the data worse than the entry it seeded from
            # must not supersede it (generational drift). Runs that
            # write no residuals bank quality-less (always supersede).
            quality = (self._prior_res2 / self._prior_res_tiles
                       if self._prior_res_tiles else None)
            ppriors.PRIORS.bank(p.prior_key(), Jt, times,
                                [float(p.ms.meta["freq0"])],
                                quality=quality,
                                jones_mode=getattr(
                                    p.cfg, "jones_mode", "full"))
        except Exception as e:
            self.log(f"prior-cache: bank skipped ({e})")

    def _save_checkpoint(self, state: dict) -> None:
        """Writer-thread half of the checkpoint: runs strictly after
        this tile's writes, reads the solutions file's live byte
        position (accurate — ``_write_cols`` flushed), and lands the
        sidecar atomically."""
        sol.save_checkpoint(self.ckpt_path,
                            sol_bytes=self.writer.f.tell(),
                            meta=self._ckpt_meta, **state)

    def _step_per_channel(self, ti, tile, stg, info):
        # -b 1: per-channel LBFGS re-solve + per-channel residual
        # (fullbatch_mode.cpp:442-488). Channels are independent
        # (each warm-starts from the same joint solution), so the
        # whole channel axis runs as ONE vmapped solve + ONE
        # vmapped residual program instead of a sequential loop.
        # The last channel's solutions become the carried/written
        # solutions (fullbatch_mode.cpp:485 memcpy).
        p = self.p
        cfg, ms, sky, meta = p.cfg, p.ms, p.sky, p.ms.meta
        bubble = 0.0
        u, v, w = stg["u"], stg["v"], stg["w"]
        sta1, sta2 = stg["sta1"], stg["sta2"]
        wt, flags, tile_beam = stg["wt"], stg["flags"], stg["beam"]
        J0c_r8 = jnp.asarray(utils.jones_c2r_np(self.J), p.rdt)
        flags_np = np.asarray(flags)
        F = len(tile.freqs)
        Bn = tile.x.shape[0]
        x8C = np.zeros((F, Bn, 8))
        xC = np.zeros((F, Bn, 2, 2), np.complex128)
        badC = np.zeros((F, Bn), bool)
        for ci_ch in range(F):
            xc = np.array(tile.x[:, ci_ch])
            # per-channel flags (same data the joint pack path
            # zeroes) + row flags
            bad = flags_np == 1
            if tile.cflags is not None:
                bad = bad | (tile.cflags[:, ci_ch] != 0)
            xc[bad] = 0.0
            x8C[ci_ch] = utils.vis_to_x8(xc)
            xC[ci_ch] = xc
            badC[ci_ch] = bad
        x8C_d = jnp.asarray(x8C, p.rdt)
        if cfg.whiten:
            from sagecal_tpu.solvers import robust as rb
            x8C_d = jax.vmap(
                lambda x: rb.whiten_data(x, u, v, meta["freq0"])
            )(x8C_d)
        # channel-flagged rows carry zero weight in THEIR
        # channel's solve (zeroed data must not pull the fit)
        wtC = wt[None] * jnp.asarray(~badC, p.rdt)[:, :, None]
        freqsC = jnp.asarray(tile.freqs, p.rdt)
        # blocks of channels: one vmapped execution per block so a
        # wide band cannot exceed the tunneled chip's per-execution
        # wall-clock kill; the last block is padded (zero weight)
        # to keep one compiled program
        CB = min(F, 16)
        nblk = -(-F // CB)
        Fp = nblk * CB
        if Fp != F:
            padc = Fp - F
            x8C_d = jnp.concatenate(
                [x8C_d, jnp.zeros((padc,) + x8C_d.shape[1:],
                                  x8C_d.dtype)])
            wtC = jnp.concatenate(
                [wtC, jnp.zeros((padc,) + wtC.shape[1:],
                                wtC.dtype)])
            freqsC = jnp.concatenate(
                [freqsC, jnp.full((padc,), freqsC[-1],
                                  freqsC.dtype)])
        JC_blocks, res_blocks = [], []
        x_rC_full = None
        if self.write_residuals:
            # PR 6 known limit made EXPLICIT: the per-channel residual
            # assembly moves axes host-side with numpy, which has no
            # bf16/f16 — this branch stages and ships PIPELINE-dtype
            # bytes regardless of --dtype-policy. One-time warning +
            # diag record of the un-melted traffic, so a service job
            # running -b 1 under a reduced policy never reports byte
            # savings it didn't get.
            x_rC_full = jnp.asarray(utils.c2r(xC[:, :, None]), p.rdt)
            if p.dtype_policy != "f32" and not getattr(
                    p, "_warned_b1_dtype", False):
                p._warned_b1_dtype = True
                unmelted = int(x_rC_full.size) * (
                    np.dtype(p.rdt).itemsize - np.dtype(p.sdt).itemsize)
                self.log(
                    f"dtype-policy {p.dtype_policy}: the -b 1 "
                    "per-channel residual assembly is host-side numpy "
                    "(no bf16/f16) and stays at the pipeline dtype — "
                    f"~{unmelted / 1e6:.1f} MB/tile of residual "
                    "traffic is NOT melted by the storage policy")
                dtrace.emit("dtype_fallback", what="per_channel_residual",
                            policy=p.dtype_policy, tile=ti,
                            unmelted_bytes_per_tile=unmelted)
            if Fp != F:
                x_rC_full = jnp.concatenate(
                    [x_rC_full,
                     jnp.zeros((Fp - F,) + x_rC_full.shape[1:],
                               x_rC_full.dtype)])
        for blk in range(nblk):
            sl = slice(blk * CB, (blk + 1) * CB)
            JC_b, _, _ = p._chan_solver(
                x8C_d[sl], wtC[sl], freqsC[sl], u, v, w, sta1,
                sta2, J0c_r8, tile_beam)
            JC_blocks.append(np.asarray(JC_b))
            if self.write_residuals:
                res_b = p._chan_residual_fn(
                    JC_b, x_rC_full[sl], u, v, w, sta1, sta2,
                    freqsC[sl], tile_beam)
                res_blocks.append(np.asarray(res_b))
        JC_r8 = np.concatenate(JC_blocks)[:F]
        if self.write_residuals:
            resC = np.concatenate(res_blocks)[:F]
            # [F, B, 1, 2, 2] complex -> [B, F, 2, 2]
            tile.x = np.moveaxis(
                utils.r2c(resC)[:, :, 0], 0, 1
            ).astype(np.complex128)
            bubble += self.aw.submit(ms.write_tile, ti, tile)
        self.J = utils.jones_r2c_np(np.asarray(JC_r8[-1]))
        if self.writer:
            bubble += self.aw.submit(self.writer.write_interval,
                                     self.J, sky.nchunk)
        return bubble

    def close(self, raise_pending: bool = True):
        """Flush + close the job's writer thread and solution file.
        Re-raises a pending async-write failure (unless told not to —
        the scheduler's failed-job teardown path, where the failure
        was already recorded and a second raise would mask cleanup).
        A COMPLETED run (every tile stepped, writes flushed clean)
        removes its checkpoint sidecar; a failed/killed run keeps it —
        that file IS the ``resume=true`` re-entry point."""
        if raise_pending and self._prior_banked and (
                self.open_ended
                or (self.n_tiles is not None
                    and self._last_tile >= self.n_tiles - 1)):
            # prior-store write-back rides the ORDERED writer thread:
            # submitted after every tile's writes and before the close
            # flush, so a banked prior can only ever name a chain
            # whose outputs durably landed. Open-ended (stream) jobs
            # bank whatever accumulated at their clean close — a live
            # stream has no "last tile", EndOfStream is the end.
            self.aw.submit(self._bank_priors)
        try:
            self.aw.close(raise_pending=raise_pending)
        finally:
            if self.writer:
                self.writer.close()
        if raise_pending and self.ckpt_path \
                and self.n_tiles is not None \
                and self._last_tile >= self.n_tiles - 1:
            try:
                os.remove(self.ckpt_path)
            except OSError:
                pass


def run(cfg: RunConfig, log=print):
    """Open dataset + sky model, dispatch fullbatch or simulation.

    The three run modes of the reference main.cpp:288-299 (fullbatch /
    stochastic / stochastic-consensus) dispatch here; stochastic modes live
    in sagecal_tpu.stochastic. ``stream_source`` set dispatches the
    live-ingest driver (sagecal_tpu.stream; MIGRATION.md "Streaming
    mode") — the transport owns dataset materialization.
    """
    strm = None
    if getattr(cfg, "stream_source", None):
        from sagecal_tpu import stream as tstream
        strm, ms = tstream.open_stream(cfg, log=log)
    else:
        ms = ds.open_dataset(cfg.ms, cfg.ms_list, tilesz=cfg.tile_size,
                             data_column=cfg.input_column,
                             out_column=cfg.output_column)
    meta = ms.meta
    sky = skymodel.read_sky_cluster(cfg.sky_model, cfg.cluster_file,
                                    meta["ra0"], meta["dec0"], meta["freq0"],
                                    cfg.format_3)
    pipe = FullBatchPipeline(cfg, ms, sky, log=log)
    if strm is not None:
        return pipe.run(solution_path=cfg.solutions_file, log=log,
                        stream=strm)
    if cfg.simulation != SimulationMode.OFF:
        return pipe.run_simulation(log=log)
    return pipe.run(solution_path=cfg.solutions_file,
                    max_tiles=cfg.max_timeslots or None, log=log)
