"""sagecal_tpu.obs: production observability over the diag tracer.

Four pieces, one contract:

- :mod:`obs.metrics` — a zero-dependency, thread-safe metrics registry
  (counters, gauges, fixed-bucket histograms with percentile readout)
  with the same no-op-when-disabled promise as ``diag.trace``: until
  :func:`metrics.enable` installs a registry, every emit helper costs
  one attribute load and one ``is None`` test, and emit sites whose
  field conversion would force a device sync gate on
  ``metrics.active()`` exactly like ``dtrace.active()`` (both gates
  are blessed by the jaxlint host-sync checker).
- :mod:`obs.health` — live convergence health: streaming
  stall/divergence detection over per-solve residual records (a
  monotone-residual watermark with configurable patience), so a
  diverging job is visible *before* it burns its full tile budget.
- :mod:`obs.export` — Prometheus text exposition of a registry plus
  the stdlib HTTP endpoint serving ``/metrics`` and ``/healthz`` for
  the serve daemon (``--metrics-port``).
- :mod:`obs.sentinel` — the perf-regression sentinel: loads the newest
  round-stamped ``BENCH_<PLAT>_rNN.json`` bank and fails (non-zero
  exit, named metric) on regression beyond per-metric tolerances —
  the Δbytes/Δwall discipline CHANGES.md used to enforce by hand,
  machine-enforced (CI lane + bench.py post-run check).

Layering: stdlib only, like ``diag.trace`` — the solver and pipeline
layers import ``obs.metrics`` unconditionally and an import that
pulled in jax from inside ``sagecal_tpu.solvers.sage`` would be a
layering inversion. (``obs.sentinel``'s full mode imports bench
lazily; ``--fast`` stays stdlib + the repo's own modules.)
"""

from sagecal_tpu.obs import metrics  # noqa: F401  (the common entry)
