"""Prometheus text exposition + the daemon's HTTP observability port.

Two halves, both stdlib-only:

- :func:`render_prometheus` serializes an :class:`obs.metrics.Registry`
  into Prometheus text format 0.0.4 (``# TYPE`` headers,
  ``_bucket{le="..."}`` / ``_sum`` / ``_count`` histogram series) so
  the serve daemon is scrapeable by stock tooling. Every metric name
  is prefixed ``sagecal_`` at render time; emit sites keep short
  names.
- :class:`ObsHTTPServer` is a tiny threaded ``http.server`` exposing

  - ``GET /metrics``  — text format; the provider callback runs first
    so point-in-time gauges (queue depth, device busy) are fresh;
  - ``GET /healthz``  — JSON; HTTP 200 when healthy, 503 when the
    provider reports ``status: degraded`` (a stalled/diverging job, a
    stuck device) — the shape load balancers and probes expect.

  It serves observability ONLY: no request mutates server state, so
  binding it wider than localhost leaks information, not control.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PREFIX = "sagecal_"


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(pairs) -> str:
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in pairs)
    return "{" + body + "}"


def render_prometheus(registry) -> str:
    """Text exposition of every metric in ``registry`` (sorted, so the
    output is diffable and the golden test is stable)."""
    from sagecal_tpu.obs.metrics import Counter, Gauge, Histogram
    lines = []
    with registry._lock:
        for name, m in sorted(registry._metrics.items()):
            full = PREFIX + name
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.kind}")
            for key, s in sorted(m.series().items()):
                if isinstance(m, (Counter, Gauge)):
                    lines.append(
                        f"{full}{_fmt_labels(key)} {_fmt_value(s[0])}")
                elif isinstance(m, Histogram):
                    cum = 0
                    for ub, c in zip(list(m.buckets) + [float("inf")],
                                     s.counts):
                        cum += c
                        le = _fmt_value(ub) if ub != float("inf") \
                            else "+Inf"
                        lines.append(
                            f"{full}_bucket"
                            f"{_fmt_labels(list(key) + [('le', le)])} "
                            f"{cum}")
                    lines.append(f"{full}_sum{_fmt_labels(key)} "
                                 f"{_fmt_value(s.sum)}")
                    lines.append(f"{full}_count{_fmt_labels(key)} "
                                 f"{s.count}")
    return "\n".join(lines) + "\n"


class ObsHTTPServer:
    """Threaded HTTP endpoint for ``/metrics`` + ``/healthz``.

    ``metrics_provider()`` -> Prometheus text (str);
    ``health_provider()`` -> JSON-serializable dict whose ``status``
    key selects the HTTP code (``ok`` -> 200, anything else -> 503).
    Provider exceptions answer 500 with the error text instead of
    killing the serving thread.
    """

    def __init__(self, port: int, metrics_provider, health_provider,
                 host: str = "127.0.0.1"):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):        # quiet: probes are chatty
                pass

            def _reply(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = outer.metrics_provider().encode()
                        self._reply(200, body,
                                    "text/plain; version=0.0.4; "
                                    "charset=utf-8")
                    elif path == "/healthz":
                        h = outer.health_provider()
                        code = 200 if h.get("status") == "ok" else 503
                        self._reply(code,
                                    (json.dumps(h) + "\n").encode(),
                                    "application/json")
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except Exception as e:      # keep the probe port alive
                    self._reply(500, f"{type(e).__name__}: {e}\n"
                                .encode(), "text/plain")

        self.metrics_provider = metrics_provider
        self.health_provider = health_provider

        class Srv(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv((host, int(port)), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            kwargs={"poll_interval": 0.2}, name="obs-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
