"""Perf-regression sentinel over the round-stamped bench bank.

The repo's perf discipline lives in the committed
``BENCH_<PLAT>_rNN.json`` records: every round banks wall-clock,
bytes/step (XLA cost analysis + executed-trip pricing), pipeline
bubble / device-busy fractions and compile-cache hit rates, and
CHANGES.md has enforced "no silent regression" by hand ever since the
Δbytes column landed. This module machine-enforces it:

- :func:`compare` — live-vs-bank comparison of one results dict
  against another, per-metric tolerances (:data:`TOLERANCES`),
  direction-aware (an *improvement* never fails). Records are only
  compared when their ``shape`` strings match: a re-shaped config is
  a different experiment, not a regression.
- **Cross-round check** — for every config, its newest banked
  occurrence is compared against the most recent earlier round that
  carried it, so a PR that banks a regressed round fails CI at the
  bank, before anyone reads the table.
- **Live probes** — fast in-process re-measurements of the three
  structural metrics that can rot without any bank being written:
  the overlap machinery still overlaps (``sched`` primitives hide a
  producer behind a consumer), the serve program cache still shares
  (a second bucket-compatible pipeline adds ZERO compiles), and the
  fault-injection layer stays compile-free (a run under an inert
  fault plan adds ZERO compiles — the faults-off zero-cost
  contract, ISSUE 10).
- **Full mode** (no ``--fast``) — additionally re-runs the fast bench
  configs (:data:`RERUN_CONFIGS`) through bench.py's subprocess
  driver and compares the fresh numbers against the bank.

Exit status: 0 clean, 1 regression (each violation printed with its
named metric), 2 usage / unreadable bank. Wired as a CI lane
(``python -m sagecal_tpu.obs.sentinel --fast``) and as bench.py's
post-run check (each fresh record is compared as it lands and the
violations are stored in the stamped JSON).

Tolerances are deliberately asymmetric per metric: bytes/step comes
from XLA cost analysis and is near-deterministic (2%), wall-clock on
shared hosts is noisy (30%), busy/cache fractions get small absolute
slack. :data:`TABLE_COLUMNS` names the BENCH_TABLE.md column each
toleranced metric is read from; ``bench.write_table`` asserts the
mapping against the header it renders, so the sentinel can never
drift from the table silently.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

HERE = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: per-metric regression tolerances. ``rel`` = fraction of the banked
#: value, ``abs`` = absolute slack; ``better`` gives the healthy
#: direction (the other direction is never a violation).
TOLERANCES = {
    "wall": dict(field="step_s", rel=0.30, better="lower"),
    "bytes": dict(field="bytes_accessed", rel=0.02, better="lower"),
    "bubble": dict(field="device_busy_frac", abs=0.05, better="higher"),
    "cache": dict(field="cache_hit_rate", abs=0.02, better="higher"),
}

#: BENCH_TABLE.md column each toleranced metric is read from (None:
#: the metric lives in the record / shape column only). bench.write_table
#: asserts this mapping against the header it renders.
TABLE_COLUMNS = {"wall": "step", "bytes": "Δbytes",
                 "bubble": None, "cache": None}

#: bench configs cheap enough to re-run live in full (non ``--fast``)
#: mode — minutes, not the full bench's half hour.
RERUN_CONFIGS = ("2-stochastic-lbfgs", "6-overlap-e2e")

#: fleet-record tolerances (FLEET_rNN.json, bench config
#: 9-fleet-throughput — its own record family, like BSCALING): the
#: 1->2-device throughput scaling, per-device fleet throughput, p99
#: queue wait on the fleet leg, and the WORST per-device compile-cache
#: hit rate (a placement regression shows up as one device going
#: cold). Judged cross-round exactly like the BENCH banks.
FLEET_TOLERANCES = {
    "scaling": dict(field="scaling_1to2", abs=0.15, better="higher"),
    "fleet_throughput": dict(
        field="throughput_per_device_2dev_jobs_h", rel=0.30,
        better="higher"),
    "queue_wait": dict(field="p99_queue_wait_2dev_s", rel=0.50,
                       better="lower"),
    "fleet_cache": dict(field="cache_hit_rate_min_2dev", abs=0.02,
                        better="higher"),
}

#: 2-D mesh record tolerances (MESH2D_rNN.json, tools_dev/northstar.py
#: --mesh2d — the freq x time pod-slice record family, ISSUE 14):
#: per-ADMM-iteration wall on the mesh leg, the measured collective-
#: overhead fraction (consensus program wall / body-iteration wall —
#: the "consensus is free" claim as a number), and the residual-parity
#: flag vs the sequential warm-start chain (gated at bank time; a
#: record banking parity_ok=0 — or a later round losing it — fails CI
#: with the metric named). Judged cross-round like FLEET_TOLERANCES.
MESH_TOLERANCES = {
    "mesh_wall": dict(field="wall_per_admm_iter_s", rel=0.30,
                      better="lower"),
    "mesh_collective": dict(field="collective_overhead_frac", abs=0.02,
                            better="lower"),
    "mesh_parity": dict(field="parity_ok", abs=0.0, better="higher"),
}

#: cross-process scale-out tolerances (SCALEOUT_rNN.json, bench config
#: 10-scaleout — the router + W worker PROCESSES record family, ISSUE
#: 15): the 1->2-WORKER aggregate throughput scaling, the 2-worker
#: p99 queue wait, the WORST per-worker compile-cache hit rate on the
#: timed legs (a routing regression shows up as one worker's cache
#: going cold), the worker-loss recovery wall, and the recovery's
#: tiles-re-run count — banked 0; a later round re-running ANY
#: completed tile after a crash fails CI with the metric named.
#: Judged cross-round like FLEET/MESH_TOLERANCES.
SCALEOUT_TOLERANCES = {
    "scaleout_scaling": dict(field="scaling_1to2", abs=0.15,
                             better="higher"),
    "scaleout_queue_wait": dict(field="p99_queue_wait_2w_s", rel=0.50,
                                better="lower"),
    "scaleout_cache": dict(field="cache_hit_rate_min_2w", abs=0.02,
                           better="higher"),
    "scaleout_recovery_wall": dict(field="recovery_wall_s", rel=0.50,
                                   better="lower"),
    "scaleout_recovery_rerun": dict(field="recovery_tiles_rerun",
                                    abs=0.0, better="lower"),
}

#: streaming-calibration tolerances (STREAM_rNN.json, bench config
#: 11-stream-latency — live tile ingest with arrival-to-write latency
#: as the SLO, ISSUE 16): the p99 arrival->durable-residual latency
#: while a batch job shares the device, the late-tile fraction
#: (banked 0 — a later round missing ANY deadline regresses the SLO
#: itself, not just the tail), and the batch tiles re-run across
#: stream preemptions — banked 0; preemption must resume from
#: checkpoint, never replay. Judged cross-round like the FLEET/
#: MESH2D/SCALEOUT families.
STREAM_TOLERANCES = {
    "stream_p99_latency": dict(field="p99_latency_s", rel=0.50,
                               better="lower"),
    "stream_late_frac": dict(field="late_frac", abs=0.0,
                             better="lower"),
    "stream_batch_rerun": dict(field="batch_tiles_rerun", abs=0.0,
                               better="lower"),
}

#: warm-start prior-cache tolerances (WARM_rNN.json, bench config
#: 12-warm-start — content-keyed solution reuse across jobs, ISSUE
#: 18): the fraction of solver sweeps the prior seed saves on repeat-
#: field jobs vs the cold control, the warm wall per job, the warm/
#: cold final-residual ratio (the tolerance-not-bit quality envelope
#: — warm must CONVERGE as well, just in fewer sweeps; the bench
#: refuses to bank when this regresses), and the prior-store +
#: router prior-affinity hit rates on the repeat stream. Judged
#: cross-round like the FLEET/MESH2D/SCALEOUT/STREAM families.
WARM_TOLERANCES = {
    "warm_sweeps_reduction": dict(field="sweeps_reduction_frac",
                                  abs=0.15, better="higher"),
    "warm_wall_per_job": dict(field="wall_per_job_warm_s", rel=0.50,
                              better="lower"),
    "warm_residual_ratio": dict(field="residual_ratio_warm_vs_cold",
                                abs=0.05, better="lower"),
    "warm_prior_hit_rate": dict(field="prior_hit_rate", abs=0.02,
                                better="higher"),
    "warm_router_affinity": dict(field="router_prior_affinity_hit_rate",
                                 abs=0.02, better="higher"),
}

#: kernel-melt tolerances (BSCALING_rNN.json, tools_dev/northstar.py
#: --b-scaling --inner both --kernel both — the kernel on/off x inner
#: chol/cg ladder, ISSUE 17): the pallas-vs-xla per-cluster delta in
#: PERCENT at the full-B rung (the fused-chol melt headline) and at
#: the quarter-B floor, per inner, plus the cg-vs-chol price under
#: the pallas kernel. All fields are signed percentages (negative =
#: pallas/cg cheaper), so slack is ABSOLUTE percentage points — a
#: relative slack on a near-zero or negative delta would be
#: meaningless. Fields absent from the earlier round (the round-17
#: full-B/small-rung additions vs r11) are skipped by ``compare`` and
#: start being judged the first round after both sides carry them.
KMELT_TOLERANCES = {
    "kmelt_full_chol": dict(field="full_pallas_vs_xla_pct_chol",
                            abs=8.0, better="lower"),
    "kmelt_floor_chol": dict(field="floor_pallas_vs_xla_pct_chol",
                             abs=8.0, better="lower"),
    "kmelt_floor_cg": dict(field="floor_pallas_vs_xla_pct_cg",
                           abs=15.0, better="lower"),
    "kmelt_cg_price": dict(field="cg_vs_chol_pct_pallas",
                           abs=80.0, better="lower"),
}

#: constrained-Jones melt tolerances (JONES_rNN.json, bench config
#: 13-jones-melt — diag/phase solver paths that shrink the per-
#: baseline Gram traffic 8x8 -> 2x2, ISSUE 20): the phase- and diag-
#: mode bytes/trip RATIOS vs the full-Jones path under both kernels
#: (the melt headline — a later round fattening a ratio is the
#: reduced path silently re-densifying), plus two boolean gates the
#: bench itself refuses to bank without: the constrained-truth
#: residual envelope (diag/phase must still CONVERGE, within 5% of
#: full's residual norm on a constrained truth) and full-mode bit-
#: identity (jones_mode="full" must stay byte-identical to the
#: pre-mode solver). Ratio slack is ABSOLUTE — the banked values sit
#: near zero, so a relative slack would be meaningless.
JONES_TOLERANCES = {
    "jones_phase_bytes_xla": dict(field="phase_bytes_ratio_xla",
                                  abs=0.05, better="lower"),
    "jones_phase_bytes_pallas": dict(field="phase_bytes_ratio_pallas",
                                     abs=0.05, better="lower"),
    "jones_diag_bytes_xla": dict(field="diag_bytes_ratio_xla",
                                 abs=0.05, better="lower"),
    "jones_diag_bytes_pallas": dict(field="diag_bytes_ratio_pallas",
                                    abs=0.05, better="lower"),
    "jones_residual_envelope": dict(field="residual_envelope_met",
                                    abs=0.0, better="higher"),
    "jones_full_bit_identity": dict(field="full_mode_bit_identical",
                                    abs=0.0, better="higher"),
}


def assert_table_contract(header: str) -> None:
    """Every toleranced metric with a named table column must find it
    in the header bench.write_table is about to render."""
    for metric, col in TABLE_COLUMNS.items():
        if col is not None and col not in header:
            raise AssertionError(
                f"sentinel metric {metric!r} reads BENCH_TABLE column "
                f"{col!r}, absent from the rendered header: {header}")
    missing = set(TOLERANCES) - set(TABLE_COLUMNS)
    if missing:
        raise AssertionError(
            f"sentinel tolerances {sorted(missing)} have no "
            f"TABLE_COLUMNS entry")


# ---------------------------------------------------------------------------
# bank loading
# ---------------------------------------------------------------------------

def load_banks(platform: str, bank_dir: str = HERE,
               pattern: str | None = None):
    """All round-stamped records of ``platform``, oldest first:
    ``[(round, path, results_dict), ...]``. Records whose declared
    platform mismatches their filename are skipped (the bank-hygiene
    rule bench.py enforces on write). ``pattern`` overrides the
    BENCH filename family (the FLEET loader reuses this body)."""
    out = []
    pat = os.path.join(bank_dir,
                       pattern or f"BENCH_{platform.upper()}_r*.json")
    for p in sorted(glob.glob(pat)):
        m = re.search(r"_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as f:
                d = json.load(f)
        except Exception:
            continue
        if d.get("platform") != platform:
            continue
        res = d.get("results")
        if isinstance(res, dict) and res:
            out.append((int(m.group(1)), p, res))
    out.sort(key=lambda t: t[0])
    return out


def newest_bank_results(platform: str, bank_dir: str = HERE) -> dict:
    """Per-config newest banked record across all rounds (a config
    absent from the newest round keeps its last banked occurrence) —
    what a live run measures against."""
    merged: dict = {}
    for _, _, res in load_banks(platform, bank_dir):
        for name, rec in res.items():
            if isinstance(rec, dict) and "error" not in rec:
                merged[name] = rec
    return merged


# ---------------------------------------------------------------------------
# comparison core
# ---------------------------------------------------------------------------

def _limit(banked: float, spec: dict) -> float:
    slack = banked * spec["rel"] if "rel" in spec else spec["abs"]
    return banked + slack if spec["better"] == "lower" else banked - slack


def compare(live: dict, bank: dict, tolerances: dict | None = None,
            source: str = "bank") -> list:
    """Violations of ``live`` results vs ``bank`` results (both
    ``{config: record}``). Shape-guarded: records with differing
    ``shape`` strings are different experiments and are skipped, as
    are FAILED records and absent fields. Returns a list of dicts,
    each carrying the NAMED metric (the acceptance contract: a
    failure must say which metric regressed where)."""
    tolerances = TOLERANCES if tolerances is None else tolerances
    out = []
    for name, lrec in live.items():
        brec = bank.get(name)
        if not isinstance(lrec, dict) or not isinstance(brec, dict):
            continue
        if "error" in lrec or "error" in brec:
            continue
        if lrec.get("shape") != brec.get("shape"):
            continue                      # re-shaped config: no claim
        for metric, spec in tolerances.items():
            lv, bv = lrec.get(spec["field"]), brec.get(spec["field"])
            if lv is None or bv is None:
                continue
            lv, bv = float(lv), float(bv)
            lim = _limit(bv, spec)
            bad = lv > lim if spec["better"] == "lower" else lv < lim
            if bad:
                out.append({
                    "config": name, "metric": metric,
                    "field": spec["field"], "live": lv, "banked": bv,
                    "limit": lim, "source": source,
                    "msg": (f"{name}/{metric} ({spec['field']}): "
                            f"live {lv:.6g} vs {source} {bv:.6g} "
                            f"(limit {lim:.6g})")})
    return out


def load_fleet_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped fleet records (FLEET_rNN.json), oldest first —
    :func:`load_banks` over the fleet filename family (one series on
    disk, filtered by the declared platform)."""
    return load_banks(platform, bank_dir, pattern="FLEET_r*.json")


def load_mesh_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped 2-D mesh records (MESH2D_rNN.json), oldest first
    — :func:`load_banks` over the mesh filename family."""
    return load_banks(platform, bank_dir, pattern="MESH2D_r*.json")


def load_scaleout_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped cross-process scale-out records
    (SCALEOUT_rNN.json), oldest first."""
    return load_banks(platform, bank_dir, pattern="SCALEOUT_r*.json")


def load_stream_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped streaming-calibration records (STREAM_rNN.json),
    oldest first."""
    return load_banks(platform, bank_dir, pattern="STREAM_r*.json")


def load_warm_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped warm-start prior-cache records (WARM_rNN.json),
    oldest first."""
    return load_banks(platform, bank_dir, pattern="WARM_r*.json")


def load_jones_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped constrained-Jones melt records (JONES_rNN.json),
    oldest first."""
    return load_banks(platform, bank_dir, pattern="JONES_r*.json")


def load_kmelt_banks(platform: str, bank_dir: str = HERE):
    """Round-stamped kernel-melt ladders (BSCALING_rNN.json), oldest
    first. BSCALING records predate :func:`bench.stamp_family` and are
    BARE — no ``{"results": {...}}`` envelope — so this loader adapts
    them to the ``load_banks`` tuple shape by wrapping each record
    under the single config name ``"b-scaling"``. Platform hygiene is
    the same: a record whose declared platform mismatches is skipped.
    Round 7 (chol-vs-cg only, no kernel axis) carries none of the
    :data:`KMELT_TOLERANCES` fields and drops out of the comparison
    via the absent-field guard in :func:`compare`."""
    out = []
    for p in sorted(glob.glob(os.path.join(bank_dir,
                                           "BSCALING_r*.json"))):
        m = re.search(r"_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as f:
                d = json.load(f)
        except Exception:
            continue
        if d.get("platform") != platform:
            continue
        out.append((int(m.group(1)), p, {"b-scaling": d}))
    out.sort(key=lambda t: t[0])
    return out


def _family_cross_round_check(banks, tolerances: dict,
                              tag: str) -> list:
    """Newest round of a record family vs the most recent earlier one,
    judged against ``tolerances`` — the shared body of the FLEET and
    MESH2D cross-round checks (same final-pair-only discipline as
    :func:`cross_round_check`)."""
    occ: dict = {}
    for rnd, _path, res in banks:
        for name, rec in res.items():
            if isinstance(rec, dict) and "error" not in rec:
                occ.setdefault(name, []).append((rnd, rec))
    viol = []
    for name, pairs in occ.items():
        if len(pairs) < 2:
            continue
        (prnd, prev), (rnd, rec) = pairs[-2], pairs[-1]
        for v in compare({name: rec}, {name: prev},
                         tolerances=tolerances,
                         source=f"{tag} r{prnd:02d}"):
            v["round"] = rnd
            v["msg"] = f"{tag} r{rnd:02d} " + v["msg"]
            viol.append(v)
    return viol


def fleet_cross_round_check(platform: str, bank_dir: str = HERE) -> list:
    """Newest fleet round vs the most recent earlier one, judged
    against :data:`FLEET_TOLERANCES` — a PR that banks a fleet round
    with collapsed scaling, a blown queue-wait tail, or a cold
    per-device cache fails CI with the metric named (the ISSUE 12
    satellite: fleet bench metrics join the sentinel like the
    existing banks)."""
    return _family_cross_round_check(
        load_fleet_banks(platform, bank_dir), FLEET_TOLERANCES, "FLEET")


def mesh_cross_round_check(platform: str, bank_dir: str = HERE) -> list:
    """Newest 2-D mesh round vs the most recent earlier one, judged
    against :data:`MESH_TOLERANCES` — a later round regressing the
    mesh wall/iter, fattening the collective-overhead fraction, or
    losing residual parity vs the sequential chain fails CI with the
    metric named (the ISSUE 14 satellite, mirroring the fleet
    family)."""
    return _family_cross_round_check(
        load_mesh_banks(platform, bank_dir), MESH_TOLERANCES, "MESH2D")


def scaleout_cross_round_check(platform: str,
                               bank_dir: str = HERE) -> list:
    """Newest scale-out round vs the most recent earlier one, judged
    against :data:`SCALEOUT_TOLERANCES` — a later round collapsing the
    cross-process throughput scaling, blowing the fleet queue-wait
    tail, going cache-cold on a worker, slowing worker-loss recovery,
    or RE-RUNNING completed tiles after a crash fails CI with the
    metric named (the ISSUE 15 satellite, mirroring the FLEET and
    MESH2D families)."""
    return _family_cross_round_check(
        load_scaleout_banks(platform, bank_dir), SCALEOUT_TOLERANCES,
        "SCALEOUT")


def stream_cross_round_check(platform: str,
                             bank_dir: str = HERE) -> list:
    """Newest streaming round vs the most recent earlier one, judged
    against :data:`STREAM_TOLERANCES` — a later round fattening the
    p99 arrival->write tail, missing ANY per-tile deadline, or
    re-running batch tiles across stream preemptions fails CI with
    the metric named (the ISSUE 16 satellite, mirroring the FLEET,
    MESH2D and SCALEOUT families)."""
    return _family_cross_round_check(
        load_stream_banks(platform, bank_dir), STREAM_TOLERANCES,
        "STREAM")


def warm_cross_round_check(platform: str,
                           bank_dir: str = HERE) -> list:
    """Newest warm-start round vs the most recent earlier one, judged
    against :data:`WARM_TOLERANCES` — a later round shrinking the
    sweeps the prior seed saves, slowing the warm wall per job,
    letting warm convergence quality drift off the cold control, or
    going cold on the prior-store / router prior-affinity hit rates
    fails CI with the metric named (the ISSUE 18 satellite, mirroring
    the FLEET/MESH2D/SCALEOUT/STREAM families)."""
    return _family_cross_round_check(
        load_warm_banks(platform, bank_dir), WARM_TOLERANCES, "WARM")


def jones_cross_round_check(platform: str,
                            bank_dir: str = HERE) -> list:
    """Newest constrained-Jones round vs the most recent earlier one,
    judged against :data:`JONES_TOLERANCES` — a later round fattening
    the diag/phase bytes-per-trip ratio under either kernel (the
    reduced Gram path re-densifying), dropping the constrained-truth
    residual envelope, or losing full-mode bit-identity fails CI with
    the metric named (the ISSUE 20 satellite, mirroring the FLEET/
    MESH2D/SCALEOUT/STREAM/WARM families)."""
    return _family_cross_round_check(
        load_jones_banks(platform, bank_dir), JONES_TOLERANCES,
        "JONES")


def kmelt_cross_round_check(platform: str,
                            bank_dir: str = HERE) -> list:
    """Newest kernel-melt round vs the most recent earlier one, judged
    against :data:`KMELT_TOLERANCES` — a later round regressing the
    fused-chol pallas-vs-xla delta at full B, fattening the quarter-B
    floor under either inner, or inflating the cg trip price under the
    kernel fails CI with the metric named (the ISSUE 17 satellite,
    mirroring the FLEET/MESH2D/SCALEOUT/STREAM families). The compare
    body is shape-guarded: a ladder banked at a different north-star
    shape makes no cross-round claim."""
    return _family_cross_round_check(
        load_kmelt_banks(platform, bank_dir), KMELT_TOLERANCES,
        "KMELT")


def cross_round_check(platform: str, bank_dir: str = HERE) -> list:
    """For every config: its NEWEST banked occurrence vs the most
    recent earlier round carrying it. Only the final pair is judged —
    the check exists to stop the next regression from landing, not to
    relitigate host changes deep in the committed history (the r05->
    r06 CPU wall jump was a different machine and predates the
    sentinel; it stays banked, annotated by its round's PERF.md)."""
    occ: dict = {}              # config -> [(round, record), ...]
    for rnd, _path, res in load_banks(platform, bank_dir):
        for name, rec in res.items():
            if isinstance(rec, dict) and "error" not in rec:
                occ.setdefault(name, []).append((rnd, rec))
    viol = []
    for name, pairs in occ.items():
        if len(pairs) < 2:
            continue
        (prnd, prev), (rnd, rec) = pairs[-2], pairs[-1]
        for v in compare({name: rec}, {name: prev},
                         source=f"r{prnd:02d}"):
            v["round"] = rnd
            v["msg"] = f"r{rnd:02d} " + v["msg"]
            viol.append(v)
    return viol


# ---------------------------------------------------------------------------
# live probes (fast): the structural metrics that rot without a bank
# ---------------------------------------------------------------------------

def probe_overlap() -> list:
    """The sched primitives still hide the producer behind the
    consumer: a sleep-shaped stream (8 items, 30 ms produce / 30 ms
    consume) must run well under the MEASURED serial reference (the
    same stream at depth 0 — the synchronous path). Both sides are
    measured on the same host moments apart, so load stretches them
    alike and the 0.9 bound only fails when overlap is structurally
    gone (prefetch serialized)."""
    from sagecal_tpu import sched
    n, dt = 8, 0.03

    def produce(i):
        time.sleep(dt)
        return i

    def run(depth):
        t0 = time.perf_counter()
        for _i, _item, _w in sched.Prefetcher(produce, n, depth=depth,
                                              name="sentinel"):
            time.sleep(dt)
        return time.perf_counter() - t0

    serial = run(0)
    wall = run(2)
    if wall >= 0.9 * serial:
        return [{"config": "probe", "metric": "bubble",
                 "field": "overlap_wall_s", "live": wall,
                 "banked": serial, "limit": 0.9 * serial,
                 "source": "probe",
                 "msg": (f"probe/bubble: overlapped stream took "
                         f"{wall:.3f}s of a measured {serial:.3f}s "
                         f"serial run — prefetch no longer overlaps")}]
    return []


def _mini_pipeline_env(tmp):
    """A tiny synthetic calibration environment shared by the live
    probes: returns ``(make_ms, run_pipe)`` over a one-source sky in
    ``tmp`` — small enough that a probe run is seconds, real enough
    that it exercises the whole staged-solve-residual chain."""
    import math

    import numpy as np
    import jax.numpy as jnp

    from sagecal_tpu import pipeline, skymodel
    from sagecal_tpu.io import dataset as ds
    from sagecal_tpu.rime import predict as rp
    from sagecal_tpu.serve.api import config_from_dict

    sky_path = os.path.join(tmp, "sky.txt")
    with open(sky_path, "w") as f:
        f.write("P0A 0 40 0 40 0 0 3.0 0 0 0 0 0 0 0 0 150e6\n")
    clus_path = sky_path + ".cluster"
    with open(clus_path, "w") as f:
        f.write("0 1 P0A\n")
    ra0 = (41 / 60) * math.pi / 12
    dec0 = 40 * math.pi / 180
    srcs = skymodel.parse_sky_model(sky_path, ra0, dec0, 150e6)
    sky = skymodel.build_cluster_sky(
        srcs, skymodel.parse_cluster_file(clus_path))
    dsky = rp.sky_to_device(sky, jnp.float64)
    Jt = ds.random_jones(1, sky.nchunk, 5, seed=5, scale=0.1)

    def make_ms(name, seed):
        tiles = [ds.simulate_dataset(
            dsky, n_stations=5, tilesz=2,
            freqs=np.array([150e6]), ra0=ra0, dec0=dec0, jones=Jt,
            nchunk=sky.nchunk, noise_sigma=0.01, seed=seed)]
        msdir = os.path.join(tmp, name)
        ds.SimMS.create(msdir, tiles)
        return msdir

    def run_pipe(msdir):
        cfg = config_from_dict(dict(
            ms=msdir, sky_model=sky_path, cluster_file=clus_path,
            solver_mode=0, max_em_iter=1, max_iter=2, max_lbfgs=0,
            tile_size=2, solve_fuse="on", solve_promote="off"))
        ms = ds.SimMS(msdir)
        pipe = pipeline.FullBatchPipeline(cfg, ms, sky,
                                          log=lambda *a: None)
        pipe.run(log=lambda *a: None)

    return make_ms, run_pipe


def probe_cache(workdir: str | None = None) -> list:
    """The serve program cache still shares: a second bucket-compatible
    pipeline over a tiny synthetic dataset must add ZERO compiles and
    land only cache hits (the tests/test_serve.py gate, portable to a
    bare ``--fast`` run outside pytest)."""
    import tempfile

    from sagecal_tpu.diag import guard
    from sagecal_tpu.serve import cache as pcache

    with tempfile.TemporaryDirectory(dir=workdir) as tmp:
        make_ms, run_pipe = _mini_pipeline_env(tmp)
        # both datasets simulated BEFORE the guard: simulate_dataset
        # compiles its own programs per call and is not under test
        ms_a, ms_b = make_ms("a.ms", 11), make_ms("b.ms", 50)
        run_pipe(ms_a)                         # warm: compiles allowed
        h0 = pcache.PROGRAMS.stats()["hits"]
        with guard.CompileGuard() as g:
            run_pipe(ms_b)
        hits = pcache.PROGRAMS.stats()["hits"] - h0
    viol = []
    if g.compiles != 0:
        viol.append({"config": "probe", "metric": "cache",
                     "field": "compiles", "live": float(g.compiles),
                     "banked": 0.0, "limit": 0.0, "source": "probe",
                     "msg": (f"probe/cache: second bucket-compatible "
                             f"pipeline added {g.compiles} compiles — "
                             f"the program cache no longer shares")})
    if hits <= 0:
        viol.append({"config": "probe", "metric": "cache",
                     "field": "cache_hits", "live": float(hits),
                     "banked": 1.0, "limit": 1.0, "source": "probe",
                     "msg": "probe/cache: second pipeline produced no "
                            "program-cache hits"})
    return viol


def probe_faults(workdir: str | None = None) -> list:
    """The fault-injection layer's zero-cost contract (ISSUE 10):
    with a LIVE-but-inert fault plan installed (rules that never
    match), a calibration run must add ZERO compiles — the injection
    seams are host-side and may never reach a traced body. Probed
    live because no bank records it and a regression (a seam moved
    inside jit) would silently retrace every tenant's solve."""
    import tempfile

    from sagecal_tpu import faults
    from sagecal_tpu.diag import guard

    with tempfile.TemporaryDirectory(dir=workdir) as tmp:
        make_ms, run_pipe = _mini_pipeline_env(tmp)
        ms_a, ms_b = make_ms("fa.ms", 11), make_ms("fb.ms", 50)
        run_pipe(ms_a)                         # warm: compiles allowed
        faults.enable([{"point": "ms_read", "at": [10 ** 9]}])
        try:
            with guard.CompileGuard() as g:
                run_pipe(ms_b)
        finally:
            faults.disable()
    if g.compiles:
        return [{"config": "probe", "metric": "cache",
                 "field": "compiles", "live": float(g.compiles),
                 "banked": 0.0, "limit": 0.0, "source": "probe",
                 "msg": (f"probe/faults: a run under an inert fault "
                         f"plan added {g.compiles} compiles — the "
                         "faults-off/inert path is no longer "
                         "compile-free")}]
    return []


def probe_kernel() -> list:
    """The fused-sweep kernel flag's zero-cost contract (ISSUE 11):
    flipping ``kernel`` between "xla" and "pallas" selects between two
    independently cached programs — running a pallas solve and then
    returning to the DEFAULT xla path must add ZERO compiles (the flag
    is a clean static, it never poisons the bit-frozen default's
    compile cache). Probed live because no bank records it; a
    regression here (the flag leaking into a shared cache key by
    value, or a non-static dispatch) would recompile every default
    solve the moment anyone tries the kernel."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sagecal_tpu.diag import guard
    from sagecal_tpu.solvers import lm as lm_mod

    rng = np.random.default_rng(0)
    N, T = 5, 4
    p, q = np.triu_indices(N, k=1)
    nb = len(p)
    B = nb * T
    s1 = jnp.asarray(np.tile(p, T).astype(np.int32))
    s2 = jnp.asarray(np.tile(q, T).astype(np.int32))
    cid = jnp.zeros((B,), jnp.int32)
    coh = jnp.asarray(rng.normal(size=(B, 2, 2))
                      + 1j * rng.normal(size=(B, 2, 2)), jnp.complex64)
    x8 = jnp.asarray(rng.normal(size=(B, 8)), jnp.float32)
    wt = jnp.ones((B, 8), jnp.float32)
    J0 = jnp.tile(jnp.eye(2, dtype=jnp.complex64), (1, N, 1, 1))

    @functools.partial(jax.jit, static_argnames=("kern", "inner"))
    def _solve(x8, coh, s1, s2, cid, wt, J0, kern, inner):
        cfg = lm_mod.LMConfig(itmax=3, kernel=kern, inner=inner)
        J, _ = lm_mod.lm_solve(x8, coh, s1, s2, cid, wt, J0, N,
                               row_period=nb, config=cfg)
        return J

    def solve(kern, inner="chol"):
        return _solve(x8, coh, s1, s2, cid, wt, J0,
                      kern=kern, inner=inner).block_until_ready()

    solve("xla")                               # warm the default path
    # kernel on, BOTH inner dispatches (may compile): "chol" is the
    # ISSUE 17 fused block-Cholesky stage, "cg" the matrix-free inner
    solve("pallas", "chol")
    solve("pallas", "cg")
    with guard.CompileGuard() as g:
        solve("xla")                           # back to default: cached
    if g.compiles:
        return [{"config": "probe", "metric": "cache",
                 "field": "compiles", "live": float(g.compiles),
                 "banked": 0.0, "limit": 0.0, "source": "probe",
                 "msg": (f"probe/kernel: returning to kernel='xla' "
                         f"after pallas chol+cg solves added "
                         f"{g.compiles} compiles — the kernel flag "
                         "poisons the default path's compile cache")}]
    with guard.CompileGuard() as g2:
        solve("pallas", "chol")     # re-entry: fused-chol stays cached
    if g2.compiles:
        return [{"config": "probe", "metric": "cache",
                 "field": "compiles", "live": float(g2.compiles),
                 "banked": 0.0, "limit": 0.0, "source": "probe",
                 "msg": (f"probe/kernel: re-entering the pallas "
                         f"fused-chol dispatch added {g2.compiles} "
                         "compiles — the chol stage does not cache "
                         "as its own static program")}]
    return []


def probe_jones() -> list:
    """The constrained-Jones flag's zero-cost contract (ISSUE 20):
    ``jones_mode`` selects between independently cached programs —
    solving in "diag" and "phase" and returning to the DEFAULT "full"
    path must add ZERO compiles (the mode is a clean static carried
    in the LMConfig cache key, it never poisons the bit-frozen full
    path's compile cache), and re-entering an already-executed
    constrained mode must be cached too. Probed live because no bank
    records compile counts; a regression here (the mode leaking into
    a shared cache key by value, or a data-dependent dispatch) would
    recompile every default solve the moment anyone tries a
    constrained mode."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sagecal_tpu.diag import guard
    from sagecal_tpu.solvers import lm as lm_mod

    rng = np.random.default_rng(0)
    N, T = 5, 4
    p, q = np.triu_indices(N, k=1)
    nb = len(p)
    B = nb * T
    s1 = jnp.asarray(np.tile(p, T).astype(np.int32))
    s2 = jnp.asarray(np.tile(q, T).astype(np.int32))
    cid = jnp.zeros((B,), jnp.int32)
    coh = jnp.asarray(rng.normal(size=(B, 2, 2))
                      + 1j * rng.normal(size=(B, 2, 2)), jnp.complex64)
    x8 = jnp.asarray(rng.normal(size=(B, 8)), jnp.float32)
    wt = jnp.ones((B, 8), jnp.float32)
    J0 = jnp.tile(jnp.eye(2, dtype=jnp.complex64), (1, N, 1, 1))

    @functools.partial(jax.jit, static_argnames=("jones",))
    def _solve(x8, coh, s1, s2, cid, wt, J0, jones):
        cfg = lm_mod.LMConfig(itmax=3, jones_mode=jones)
        J, _ = lm_mod.lm_solve(x8, coh, s1, s2, cid, wt, J0, N,
                               row_period=nb, config=cfg)
        return J

    def solve(jones):
        return _solve(x8, coh, s1, s2, cid, wt, J0,
                      jones=jones).block_until_ready()

    solve("full")                              # warm the default path
    # constrained modes (may compile): each is its own static program
    solve("diag")
    solve("phase")
    with guard.CompileGuard() as g:
        solve("full")                          # back to default: cached
    if g.compiles:
        return [{"config": "probe", "metric": "cache",
                 "field": "compiles", "live": float(g.compiles),
                 "banked": 0.0, "limit": 0.0, "source": "probe",
                 "msg": (f"probe/jones: returning to jones_mode="
                         f"'full' after diag+phase solves added "
                         f"{g.compiles} compiles — the jones flag "
                         "poisons the default path's compile cache")}]
    with guard.CompileGuard() as g2:
        solve("phase")        # re-entry: constrained mode stays cached
    if g2.compiles:
        return [{"config": "probe", "metric": "cache",
                 "field": "compiles", "live": float(g2.compiles),
                 "banked": 0.0, "limit": 0.0, "source": "probe",
                 "msg": (f"probe/jones: re-entering the phase-mode "
                         f"dispatch added {g2.compiles} compiles — a "
                         "constrained mode does not cache as its own "
                         "static program")}]
    return []


def _aliased_params(compiled) -> set:
    """Parameter indices the compiled executable's
    ``input_output_alias`` attribute names as donated-and-aliased.
    Parsed from the HLO text — the one representation every backend
    emits — by balanced-brace scan of the attribute payload (entries
    look like ``{ {}: (1, {}, may-alias) }``: output-index tree,
    then (param, param-index-tree, kind))."""
    txt = compiled.as_text()
    out: set = set()
    key = "input_output_alias={"
    start = txt.find(key)
    if start < 0:
        return out
    i = start + len(key) - 1
    depth, j = 0, i
    while j < len(txt):                 # balanced-brace payload scan
        if txt[j] == "{":
            depth += 1
        elif txt[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    for m in re.finditer(r"\(\s*(\d+)\s*,", txt[i:j + 1]):
        out.add(int(m.group(1)))
    return out


def probe_donation() -> list:
    """Donation ground truth (ISSUE 19): the jaxlint use-after-donate
    checker and the DonatedRing both PROMISE ``donate_argnums``
    aliases the donated input into the output — the promise the whole
    staged-buffer memory budget rests on — but only the lowered
    program knows whether XLA honored it. Compile the residual-shaped
    hot program twin-wise (donated / undonated) and read the
    executable's ``input_output_alias`` table: the donated twin must
    alias the visibility parameter, the undonated twin must not (which
    also proves the parse is not vacuously empty)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    B = 64
    # residual-shaped: params (index 0) consulted, visibilities
    # (index 1, donated in pipeline.py's _residuals jit) rewritten
    # in place — same shape/dtype out as the donated input
    J = jnp.asarray(rng.normal(size=(B, 2, 2))
                    + 1j * rng.normal(size=(B, 2, 2)), jnp.complex64)
    V = jnp.asarray(rng.normal(size=(B, 2, 2))
                    + 1j * rng.normal(size=(B, 2, 2)), jnp.complex64)

    def residuals(J, V):
        return V - J @ V @ jnp.conj(jnp.swapaxes(J, -1, -2))

    # jaxlint: disable=retrace -- one-shot probe: compiling IS the probe
    donated = jax.jit(residuals, donate_argnums=(1,)).lower(J, V).compile()
    # jaxlint: disable=retrace -- one-shot probe: compiling IS the probe
    plain = jax.jit(residuals).lower(J, V).compile()
    aliased = _aliased_params(donated)
    viol = []
    if 1 not in aliased:
        viol.append({"config": "probe", "metric": "donation",
                     "field": "input_output_alias", "live": 0.0,
                     "banked": 1.0, "limit": 1.0, "source": "probe",
                     "msg": ("probe/donation: donate_argnums=(1,) on "
                             "the residual-shaped program did NOT "
                             "alias parameter 1 in the compiled "
                             "executable — donation is a no-op on "
                             "this backend/version and the staged-"
                             "buffer memory budget is double-counted")})
    if _aliased_params(plain):
        viol.append({"config": "probe", "metric": "donation",
                     "field": "input_output_alias", "live": 1.0,
                     "banked": 0.0, "limit": 0.0, "source": "probe",
                     "msg": ("probe/donation: the UNDONATED twin "
                             "reports aliased parameters — the alias "
                             "parse is broken (vacuous probe)")})
    return viol


# ---------------------------------------------------------------------------
# full mode: re-run the fast bench configs and compare to the bank
# ---------------------------------------------------------------------------

def rerun_check(platform: str, bank_dir: str = HERE,
                timeout_s: int = 300, log=print) -> list:
    bank = newest_bank_results(platform, bank_dir)
    if not bank:
        return []
    # bench.py lives at the repo root, NOT necessarily next to the
    # bank records (--bank-dir may point at a copied/doctored set)
    sys.path.insert(0, HERE)
    try:
        import bench
    finally:
        sys.path.pop(0)
    viol = []
    for name in RERUN_CONFIGS:
        if name not in bank:
            continue
        log(f"sentinel: re-running {name} ({platform})")
        r = bench.run_config_subprocess(name, timeout_s=timeout_s,
                                        cpu=platform != "tpu")
        if "error" in r:
            viol.append({"config": name, "metric": "wall",
                         "field": "error", "live": None, "banked": None,
                         "limit": None, "source": "rerun",
                         "msg": f"{name}: re-run FAILED: {r['error']}"})
            continue
        viol.extend(compare({name: r}, bank, source="bank"))
    return viol


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m sagecal_tpu.obs.sentinel",
        description="perf-regression sentinel over the round-stamped "
                    "bench bank (non-zero exit + named metric on "
                    "regression)")
    p.add_argument("--fast", action="store_true",
                   help="bank integrity + cross-round check + live "
                        "probes only (the CI lane); without it the "
                        "fast bench configs are also re-run and "
                        "compared")
    p.add_argument("--platform", default="all",
                   choices=("cpu", "tpu", "all"),
                   help="which banked platform(s) to check")
    p.add_argument("--bank-dir", default=HERE, metavar="DIR",
                   help="directory holding BENCH_<PLAT>_rNN.json "
                        "(default: the repo root)")
    p.add_argument("--no-probes", action="store_true",
                   help="skip the live overlap/cache probes (bank-only "
                        "checks; used by tests that doctor a bank)")
    p.add_argument("--json", action="store_true",
                   help="emit the violation list as JSON on stdout")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    platforms = ("cpu", "tpu") if args.platform == "all" \
        else (args.platform,)
    checked_any = False
    viol = []
    for plat in platforms:
        banks = load_banks(plat, args.bank_dir)
        # a bank dir holding ONLY standalone family records (the
        # burn-down's scratch dir: BSCALING/MESH2D without a BENCH
        # series) is still a checkable bank — don't bail to rc 2
        if not banks and not any(
                ld(plat, args.bank_dir) for ld in
                (load_fleet_banks, load_mesh_banks,
                 load_scaleout_banks, load_stream_banks,
                 load_warm_banks, load_jones_banks,
                 load_kmelt_banks)):
            continue
        checked_any = True
        if banks:
            newest = banks[-1]
            print(f"sentinel: {plat} bank r{newest[0]:02d} "
                  f"({len(banks)} rounds, "
                  f"{os.path.basename(newest[1])})")
            viol.extend(cross_round_check(plat, args.bank_dir))
        fleet = load_fleet_banks(plat, args.bank_dir)
        if fleet:
            print(f"sentinel: {plat} fleet bank r{fleet[-1][0]:02d} "
                  f"({len(fleet)} rounds)")
            viol.extend(fleet_cross_round_check(plat, args.bank_dir))
        mesh = load_mesh_banks(plat, args.bank_dir)
        if mesh:
            print(f"sentinel: {plat} mesh bank r{mesh[-1][0]:02d} "
                  f"({len(mesh)} rounds)")
            viol.extend(mesh_cross_round_check(plat, args.bank_dir))
        so = load_scaleout_banks(plat, args.bank_dir)
        if so:
            print(f"sentinel: {plat} scaleout bank r{so[-1][0]:02d} "
                  f"({len(so)} rounds)")
            viol.extend(scaleout_cross_round_check(plat, args.bank_dir))
        strm = load_stream_banks(plat, args.bank_dir)
        if strm:
            print(f"sentinel: {plat} stream bank r{strm[-1][0]:02d} "
                  f"({len(strm)} rounds)")
            viol.extend(stream_cross_round_check(plat, args.bank_dir))
        warm = load_warm_banks(plat, args.bank_dir)
        if warm:
            print(f"sentinel: {plat} warm bank r{warm[-1][0]:02d} "
                  f"({len(warm)} rounds)")
            viol.extend(warm_cross_round_check(plat, args.bank_dir))
        jn = load_jones_banks(plat, args.bank_dir)
        if jn:
            print(f"sentinel: {plat} jones bank r{jn[-1][0]:02d} "
                  f"({len(jn)} rounds)")
            viol.extend(jones_cross_round_check(plat, args.bank_dir))
        km = load_kmelt_banks(plat, args.bank_dir)
        if km:
            print(f"sentinel: {plat} kmelt bank r{km[-1][0]:02d} "
                  f"({len(km)} rounds)")
            viol.extend(kmelt_cross_round_check(plat, args.bank_dir))
        if not args.fast:
            viol.extend(rerun_check(plat, args.bank_dir))
    if not checked_any:
        print(f"sentinel: no round-stamped bank under {args.bank_dir}",
              file=sys.stderr)
        return 2
    if not args.no_probes:
        viol.extend(probe_overlap())
        viol.extend(probe_cache())
        viol.extend(probe_faults())
        viol.extend(probe_kernel())
        viol.extend(probe_jones())
        viol.extend(probe_donation())
    if args.json:
        print(json.dumps(viol, indent=1))
    for v in viol:
        print(f"SENTINEL REGRESSION: {v['msg']}", file=sys.stderr)
    if viol:
        print(f"sentinel: FAIL ({len(viol)} violation(s))",
              file=sys.stderr)
        return 1
    print("sentinel: OK (bank consistent, probes green)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
