"""Zero-dependency, thread-safe metrics registry (counters / gauges /
fixed-bucket histograms with percentile readout).

Contract (mirrors ``diag.trace`` — the PR 1 telemetry layer):

- **No-op when disabled.** Until :func:`enable` installs the process
  registry, the module-level helpers (:func:`inc`, :func:`set_gauge`,
  :func:`observe`) cost one attribute load and one ``is None`` test.
  Emit sites whose *value conversion* is itself costly — a
  ``float(jnp...)`` device->host sync — gate on :func:`active` first,
  exactly like ``dtrace.active()``; both gates are blessed by the
  jaxlint host-sync checker (analysis/hostsync.py).
- **Never traced.** Every emit is host-side Python; nothing here may
  appear inside a jitted body, so metrics on/off adds ZERO compiles
  (gated by the retrace_guard fixture, tests/test_obs.py).
- **Thread-safe.** The serve daemon emits from the device-owner loop,
  per-job reader threads, and per-job writer threads concurrently;
  one registry lock keeps every update atomic.
- **Job attribution.** :func:`scope_labels` installs thread-local
  default labels (a stack, like ``dtrace.scope``): the serve
  scheduler wraps a job's step/reader/writer work in
  ``scope_labels(job=job_id)`` so emissions from the shared solver
  code attribute to the owning job without the solver knowing jobs
  exist. Scopes are STRICTLY thread-local — a scope installed on one
  thread is invisible to every other (tests/test_diag.py pins the
  same contract for tracer scopes). Label cardinality is bounded:
  past ``max_series`` distinct labelsets per metric, new labelsets
  fold into ``{...: "_overflow"}`` so totals stay correct while the
  registry stays O(1) per long-lived daemon.

Histograms use fixed buckets (default: a latency ladder from 1 ms to
600 s) so the readout is mergeable and Prometheus-compatible;
:meth:`Histogram.percentile` interpolates within the bucket the way
``histogram_quantile`` does. Declare custom buckets up front with
:meth:`Registry.histogram`; an :func:`observe` on an undeclared name
auto-creates the default ladder.
"""

from __future__ import annotations

import threading

from sagecal_tpu.analysis import threadsan

#: default histogram ladder (seconds): latency-shaped, 1 ms .. 600 s.
#: Kept coarse on purpose — SLO readout needs p50/p90/p99 stability,
#: not microsecond resolution, and every bucket is one counter per
#: labelset forever.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0, 600.0)

#: per-TILE latency ladder (seconds), 1 ms .. 60 s. The job-scale
#: ladder (serve.queue.JOB_SLO_BUCKETS) starts at 100 ms, which clamps
#: p50/p99 for tile-scale arrival-to-write latencies — a 5 ms tile and
#: a 95 ms tile land in the same bucket. Streaming SLO histograms
#: (stream_tile_latency_seconds) declare with THIS ladder: dense below
#: 100 ms where live-tile latency budgets actually live, capped at
#: 60 s because a tile a minute late is simply "late" (counted in
#: stream_tiles_late_total), not worth extra buckets.
TILE_LAT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.02, 0.035, 0.05,
                    0.075, 0.1, 0.15, 0.25, 0.4, 0.6, 1.0, 1.5, 2.5,
                    4.0, 6.0, 10.0, 20.0, 40.0, 60.0)

_REGISTRY = None            # module-level singleton; None = disabled

# thread-scoped default labels (serve: per-job attribution). A stack,
# so scopes nest; strictly thread-local, like diag.trace._SCOPED.
_SCOPED = threading.local()


def _scoped_labels() -> dict:
    st = getattr(_SCOPED, "stack", None)
    if not st:
        return {}
    out: dict = {}
    for d in st:
        out.update(d)
    return out


class _LabelScope:
    __slots__ = ("_labels",)

    def __init__(self, labels):
        self._labels = labels

    def __enter__(self):
        st = getattr(_SCOPED, "stack", None)
        if st is None:
            st = _SCOPED.stack = []
        st.append(self._labels)
        return self._labels

    def __exit__(self, *exc):
        _SCOPED.stack.pop()
        return False


def scope_labels(**labels):
    """Merge ``labels`` into every emission from THIS thread while the
    context is live (innermost scope wins per key). Per-job metric
    attribution for the serve scheduler; nests, never touches other
    threads, and is safe (a no-op at emit time) when disabled."""
    return _LabelScope(labels)


def _label_key(labels: dict):
    return tuple(sorted(labels.items()))


class _Metric:
    """Base: named metric holding per-labelset series.

    Cardinality bound: past ``max_series`` distinct labelsets, new
    labelsets fold into one ``_overflow`` series (every label value
    replaced) — counters keep counting, nothing is dropped."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", max_series: int = 256):
        self.name = name
        self.help = help
        self.max_series = int(max_series)
        self._series: dict = {}

    def _resolve(self, labels: dict):
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                key = _label_key({k: "_overflow" for k in labels})
                s = self._series.get(key)
            if s is None:
                s = self._series[key] = self._new_series()
        return s

    def series(self) -> dict:
        return dict(self._series)


class Counter(_Metric):
    kind = "counter"

    def _new_series(self):
        return [0.0]

    def _inc(self, labels, value):
        if value < 0:
            raise ValueError(f"counter {self.name}: negative inc {value}")
        self._resolve(labels)[0] += value

    def value(self, **labels) -> float:
        s = self._series.get(_label_key(labels))
        return s[0] if s else 0.0

    def _dump_series(self, s):
        return s[0]


class Gauge(_Metric):
    kind = "gauge"

    def _new_series(self):
        return [0.0]

    def _set(self, labels, value):
        self._resolve(labels)[0] = value

    def value(self, **labels) -> float:
        s = self._series.get(_label_key(labels))
        return s[0] if s else 0.0

    def _dump_series(self, s):
        return s[0]


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets):
        self.counts = [0] * (n_buckets + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS,
                 max_series: int = 256):
        super().__init__(name, help, max_series)
        b = tuple(float(x) for x in buckets)
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError(f"histogram {name}: buckets must be "
                             f"strictly increasing, got {b}")
        self.buckets = b

    def _new_series(self):
        return _HistSeries(len(self.buckets))

    def _observe(self, labels, value):
        s = self._resolve(labels)
        i = 0
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                break
        else:
            i = len(self.buckets)
        s.counts[i] += 1
        s.sum += value
        s.count += 1

    def percentile(self, q: float, **labels):
        """Interpolated percentile (``q`` in [0, 1]) from the bucket
        counts, ``histogram_quantile`` style: linear within the bucket,
        the first bucket interpolates from 0, the +Inf bucket clamps to
        the last finite edge. None when the series is empty."""
        s = self._series.get(_label_key(labels))
        if s is None or s.count == 0:
            return None
        rank = q * s.count
        cum = 0
        for i, c in enumerate(s.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):       # +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i else 0.0
                hi = self.buckets[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.buckets[-1]

    def stats(self, **labels) -> dict:
        """SLO readout for one series: count/sum/mean + p50/p90/p99."""
        s = self._series.get(_label_key(labels))
        if s is None or s.count == 0:
            return {"count": 0, "sum": 0.0, "mean": None,
                    "p50": None, "p90": None, "p99": None}
        return {"count": s.count, "sum": s.sum,
                "mean": s.sum / s.count,
                "p50": self.percentile(0.5, **labels),
                "p90": self.percentile(0.9, **labels),
                "p99": self.percentile(0.99, **labels)}

    def _dump_series(self, s):
        return {"count": s.count, "sum": s.sum,
                "buckets": dict(zip([str(b) for b in self.buckets]
                                    + ["+Inf"], s.counts))}


class Registry:
    """Thread-safe collection of named metrics.

    One lock covers declaration AND update: emissions are per-tile /
    per-sweep / per-job granularity (never per-baseline), so a plain
    lock costs nothing measurable while keeping every readout a
    consistent snapshot.
    """

    def __init__(self):
        self._metrics: dict = {}
        # reentrant: declaration helpers re-enter through the
        # declare-then-update convenience paths
        self._lock = threadsan.make_rlock("Registry._lock")

    # -- declaration --------------------------------------------------------

    def _declare(self, cls, name, help="", **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already declared as {m.kind}")
            return m

    def counter(self, name, help="") -> Counter:
        return self._declare(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:
        return self._declare(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS) \
            -> Histogram:
        return self._declare(Histogram, name, help, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    # -- emission -----------------------------------------------------------

    def inc(self, name, value=1.0, **labels) -> None:
        with self._lock:
            self._declare(Counter, name)._inc(
                {**_scoped_labels(), **labels}, float(value))

    def set_gauge(self, name, value, **labels) -> None:
        with self._lock:
            self._declare(Gauge, name)._set(
                {**_scoped_labels(), **labels}, float(value))

    def observe(self, name, value, **labels) -> None:
        with self._lock:
            self._declare(Histogram, name)._observe(
                {**_scoped_labels(), **labels}, float(value))

    # -- readout ------------------------------------------------------------

    def dump(self) -> dict:
        """JSON-serializable snapshot of every metric: counters/gauges
        as values, histograms as bucket counts + count/sum + p50/p90/
        p99 per labelset (the serve ``metrics_full`` payload)."""
        with self._lock:
            out: dict = {}
            for name, m in sorted(self._metrics.items()):
                series = {}
                for key, s in m.series().items():
                    lk = ",".join(f"{k}={v}" for k, v in key) or ""
                    val = m._dump_series(s)
                    if isinstance(m, Histogram) and s.count:
                        val.update(
                            p50=m.percentile(0.5, **dict(key)),
                            p90=m.percentile(0.9, **dict(key)),
                            p99=m.percentile(0.99, **dict(key)))
                    series[lk] = val
                out[name] = {"type": m.kind, "help": m.help,
                             "series": series}
            return out

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


# ---------------------------------------------------------------------------
# module-level no-op-when-disabled API (the diag.trace pattern)
# ---------------------------------------------------------------------------

def enable() -> Registry:
    """Install (or return) the process registry; emissions start
    counting. Idempotent: the serve daemon and an embedder can both
    call it."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = Registry()
    return _REGISTRY


def disable() -> None:
    """Uninstall the process registry (no-op when disabled); emissions
    return to costing one ``is None`` test."""
    global _REGISTRY
    _REGISTRY = None


def get() -> Registry | None:
    return _REGISTRY


def active() -> bool:
    """True when a registry is installed. Emit sites whose value
    conversion is itself costly (``float(jnp...)`` device syncs) gate
    on this — the same blessed pattern as ``dtrace.active()``."""
    return _REGISTRY is not None


def inc(name, value=1.0, **labels) -> None:
    r = _REGISTRY
    if r is not None:
        r.inc(name, value, **labels)


def set_gauge(name, value, **labels) -> None:
    r = _REGISTRY
    if r is not None:
        r.set_gauge(name, value, **labels)


def observe(name, value, **labels) -> None:
    r = _REGISTRY
    if r is not None:
        r.observe(name, value, **labels)


def dump_to(path) -> None:
    """Write the live registry's dump as JSON to ``path`` and disable
    the registry — the shared ``--metrics PATH`` exit path of both
    CLIs (one definition, so the lifecycle cannot drift between
    them). No-op when disabled."""
    import json
    r = _REGISTRY
    if r is None:
        return
    try:
        with open(path, "w") as f:
            json.dump(r.dump(), f, indent=1)
    finally:
        disable()
