"""Live convergence health: streaming stall/divergence detection.

A calibration job emits a residual stream while it runs — per-solve-
interval ``res_1`` records (pipeline tile records, the serve
scheduler's per-step history) and per-sweep reductions. Today those
are only readable after the fact from a ``--diag`` trace;
:class:`ConvergenceHealth` folds the same stream *live* into one of
three states so a diverging job is visible before it burns its full
tile budget:

- ``ok``        — the monotone-residual watermark (best residual seen)
                  improved within the last ``patience`` observations;
- ``stalled``   — ``patience`` consecutive observations without a
                  relative improvement of at least ``min_improvement``
                  over the watermark;
- ``diverging`` — a non-finite residual, or a residual more than
                  ``divergence_ratio`` times the watermark (the same
                  ratio the pipeline's divergence reset keys on,
                  pipeline.RES_RATIO).

``stalled`` is advisory (a flat residual can be a converged job — a
steady-state stream fluctuating around its noise floor stops beating
the all-time-best watermark and WILL read stalled); ``diverging`` is
the alarm. Accordingly only :data:`DEGRADED` (diverging) flips
``/healthz`` to 503 — the LB-probe path must not page on converged
jobs — while :data:`UNHEALTHY` (stalled too) drives the advisory
``unhealthy_jobs`` listing. Both are annotations, never interventions:
the fail-stop / divergence-reset machinery stays where it is, this
class only makes its inputs observable. The serve scheduler feeds one
update per completed tile and surfaces the state as the job's
``health`` field in status responses and ``/healthz``
(MIGRATION.md "Observability").

Stdlib only; a caller with a finished ``--diag`` trace can replay it
through :func:`health_of_records`.
"""

from __future__ import annotations

import time

OK = "ok"
STALLED = "stalled"
DIVERGING = "diverging"

#: states worth SURFACING (the /healthz unhealthy_jobs listing)
UNHEALTHY = (STALLED, DIVERGING)

#: states worth PAGING on (/healthz answers 503): stalled is excluded
#: — a converged job's flat residual reads stalled by construction
DEGRADED = (DIVERGING,)


class ConvergenceHealth:
    """Streaming residual-watermark health over one job's solves."""

    def __init__(self, patience: int = 3, min_improvement: float = 1e-3,
                 divergence_ratio: float = 5.0):
        self.patience = max(1, int(patience))
        self.min_improvement = float(min_improvement)
        self.divergence_ratio = float(divergence_ratio)
        self.best: float | None = None    # monotone-residual watermark
        self.last: float | None = None
        self.stale = 0                    # observations since progress
        self.n = 0
        self.state = OK
        self.last_progress_t = time.time()

    def update(self, res: float, t: float | None = None) -> str:
        """Fold one residual observation; returns the new state.

        A residual of exactly 0.0 means fully flagged data, not
        convergence (the pipeline reset convention) — it is recorded
        but neither progresses nor diverges the watermark."""
        t = time.time() if t is None else float(t)
        res = float(res)
        self.n += 1
        self.last = res
        if res != res or res in (float("inf"), float("-inf")):
            self.state = DIVERGING
            return self.state
        if res == 0.0:
            return self.state
        if self.best is None:
            self.best = res
            self.last_progress_t = t
            self.state = OK
            return self.state
        if res > self.divergence_ratio * self.best:
            self.state = DIVERGING
            return self.state
        if res < self.best * (1.0 - self.min_improvement):
            self.best = res
            self.stale = 0
            self.last_progress_t = t
            self.state = OK
        else:
            self.stale += 1
            if self.stale >= self.patience:
                self.state = STALLED
            elif self.state != DIVERGING:
                self.state = OK
        return self.state

    def snapshot(self) -> dict:
        """JSON-serializable detail for status responses."""
        return {"state": self.state, "best": self.best,
                "last": self.last, "stale": self.stale,
                "observations": self.n,
                "last_progress_t": self.last_progress_t}


def health_of_records(recs, **kw) -> ConvergenceHealth:
    """Replay a diag trace's residual stream (``tile`` records'
    ``res_1``, in order) through a fresh monitor — post-hoc triage of
    a finished run with the same thresholds the live path used."""
    h = ConvergenceHealth(**kw)
    for r in recs:
        if r.get("ev") == "tile" and "res_1" in r:
            h.update(float(r["res_1"]), t=r.get("t"))
    return h
