"""Celestial coordinate transforms (vectorized, JAX-traceable).

Capability parity with reference ``src/lib/Radio/transforms.c`` (xyz2llh:35,
radec2azel:103, jd2gmst:138, radec2azel_gmst:156, precession:202) using the
same standard algorithms (WGS84 geodesy, Vallado LST/az-el, Capitaine et al.
2003 four-angle precession), implemented array-at-a-time so they can sit
inside jitted beam computations.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

ASEC2RAD = 4.848136811095359935899141e-6  # arcseconds -> radians
_J2000_JD = 2451545.0


def xyz2llh(x, y, z):
    """ITRF Cartesian (m) -> geodetic (longitude, latitude, height) on WGS84.

    Bowring's closed-form approximation, as in reference transforms.c:35.
    """
    a = 6378137.0
    f = 1.0 / 298.257223563
    b = (1.0 - f) * a
    e2 = 2 * f - f * f
    ep2 = (a * a - b * b) / (b * b)

    p = jnp.sqrt(x * x + y * y)
    lon = jnp.arctan2(y, x)
    theta = jnp.arctan(z * a / (p * b))
    st, ct = jnp.sin(theta), jnp.cos(theta)
    lat = jnp.arctan((z + ep2 * b * st**3) / (p - e2 * a * ct**3))
    slat, clat = jnp.sin(lat), jnp.cos(lat)
    r = a / jnp.sqrt(1.0 - e2 * slat * slat)
    height = p / clat - r
    return lon, lat, height


def jd2gmst(time_jd):
    """Julian date (UT1) -> Greenwich mean sidereal angle in DEGREES.

    Same truncated GMST series as reference transforms.c:138 (Vallado
    Example 3-5), including its quirk of carrying the sign through the
    day-seconds modulus.
    """
    t = (time_jd - _J2000_JD) / 36525.0
    theta = 67310.54841 + t * (
        (876600.0 * 3600.0 + 8640184.812866) + t * (0.093104 - (6.2e-5) * t)
    )
    theta = jnp.where(theta < 0, -(jnp.abs(theta) % 86400.0), theta % 86400.0)
    return (theta / 240.0) % 360.0


def jd2gmst_np(time_jd):
    """Host-side float64 GMST (degrees). JD magnitudes (~2.45e6 days) lose
    whole hours of sidereal angle in float32, so this must never route
    through a default-precision device computation."""
    time_jd = np.asarray(time_jd, np.float64)
    t = (time_jd - _J2000_JD) / 36525.0
    theta = 67310.54841 + t * (
        (876600.0 * 3600.0 + 8640184.812866) + t * (0.093104 - (6.2e-5) * t)
    )
    theta = np.where(theta < 0, -(np.abs(theta) % 86400.0), theta % 86400.0)
    return (theta / 240.0) % 360.0


def radec2azel_gmst(ra, dec, longitude, latitude, theta_gmst_deg):
    """(ra, dec) [rad] -> (az, el) [rad] given GMST angle in degrees.

    Parity: reference transforms.c:156 (Vallado Algorithm 28).
    """
    theta_lst = theta_gmst_deg + longitude * 180.0 / jnp.pi
    lha = jnp.deg2rad((theta_lst - ra * 180.0 / jnp.pi) % 360.0)

    slat, clat = jnp.sin(latitude), jnp.cos(latitude)
    sdec, cdec = jnp.sin(dec), jnp.cos(dec)
    slha, clha = jnp.sin(lha), jnp.cos(lha)

    el = jnp.arcsin(slat * sdec + clat * cdec * clha)
    sel, cel = jnp.sin(el), jnp.cos(el)
    az = jnp.arctan2(-slha * cdec / cel, (sdec - sel * slat) / (cel * clat))
    az = az % (2.0 * jnp.pi)
    return az, el


def radec2azel(ra, dec, longitude, latitude, time_jd):
    """(ra, dec) -> (az, el) at a Julian date (reference transforms.c:103)."""
    return radec2azel_gmst(ra, dec, longitude, latitude, jd2gmst(time_jd))


def precession_matrix(jd_tdb):
    """J2000 -> mean equator/equinox of date rotation, Capitaine et al. 2003.

    Returns a 3x3 rotation (reference transforms.c:202
    ``get_precession_params``; NOVAS ``precession``).
    """
    t = (jd_tdb - _J2000_JD) / 36525.0
    eps0_as = 84381.406

    psia = ((((-0.0000000951 * t + 0.000132851) * t - 0.00114045) * t
             - 1.0790069) * t + 5038.481507) * t
    omegaa = ((((0.0000003337 * t - 0.000000467) * t - 0.00772503) * t
               + 0.0512623) * t - 0.025754) * t + eps0_as
    chia = ((((-0.0000000560 * t + 0.000170663) * t - 0.00121197) * t
             - 2.3814292) * t + 10.556403) * t

    eps0 = eps0_as * ASEC2RAD
    psia = psia * ASEC2RAD
    omegaa = omegaa * ASEC2RAD
    chia = chia * ASEC2RAD

    sa, ca = jnp.sin(eps0), jnp.cos(eps0)
    sb, cb = jnp.sin(-psia), jnp.cos(-psia)
    sc, cc = jnp.sin(-omegaa), jnp.cos(-omegaa)
    sd, cd = jnp.sin(chia), jnp.cos(chia)

    # R3(chi_a) R1(-omega_a) R3(-psi_a) R1(eps_0), row-major 3x3
    return jnp.stack([
        jnp.stack([cd * cb - sb * sd * cc,
                   cd * sb * ca + sd * cc * cb * ca - sa * sd * sc,
                   cd * sb * sa + sd * cc * cb * sa + ca * sd * sc]),
        jnp.stack([-sd * cb - sb * cd * cc,
                   -sd * sb * ca + cd * cc * cb * ca - sa * cd * sc,
                   -sd * sb * sa + cd * cc * cb * sa + ca * cd * sc]),
        jnp.stack([sb * sc,
                   -sc * cb * ca - sa * cc,
                   -sc * cb * sa + cc * ca]),
    ])


def precess_radec_std(ra0, dec0, pmat):
    """Precess (ra, dec) from J2000 by ``pmat`` = :func:`precession_matrix`
    using the STANDARD spherical convention — parity with the production
    path ``precess_source_locations`` (data.cpp:1473, casacore
    Precession/MVDirection), which the pipeline calls once per run in
    beam mode (fullbatch_mode.cpp:325)."""
    pos1 = jnp.stack([
        jnp.cos(ra0) * jnp.cos(dec0),
        jnp.sin(ra0) * jnp.cos(dec0),
        jnp.sin(dec0) * jnp.ones_like(ra0),
    ])
    pos2 = jnp.einsum("ij,j...->i...", pmat, pos1)
    ra = jnp.arctan2(pos2[1], pos2[0])
    dec = jnp.arcsin(jnp.clip(pos2[2], -1.0, 1.0))
    return ra, dec


def precess_radec(ra0, dec0, pmat):
    """Precess (ra, dec) from J2000 by ``pmat`` = :func:`precession_matrix`.

    Uses the reference's (nonstandard, colatitude-style) spherical unit
    vector convention (transforms.c:266-289) so behavior matches the
    transforms.c ``precession``/``precess_source_locations_deprecated``
    path exactly; production code should use :func:`precess_radec_std`.
    """
    pos1 = jnp.stack([
        jnp.cos(ra0) * jnp.sin(dec0),
        jnp.sin(ra0) * jnp.sin(dec0),
        jnp.cos(dec0) * jnp.ones_like(ra0),
    ])
    pos2 = pmat @ pos1
    ra = jnp.arctan2(pos2[1], pos2[0])
    dec = jnp.arctan(jnp.sqrt(pos2[0] ** 2 + pos2[1] ** 2) / pos2[2])
    return ra, dec


def radec_to_lmn(ra, dec, ra0, dec0):
    """Source direction cosines relative to phase center (ra0, dec0).

    Same sign convention as reference readsky.c:341-342 and :625
    (``ll = cos(dec) sin(ra-ra0)``; stored ``nn`` carries the -1 so the
    phase center has zero fringe phase).
    """
    ll = jnp.cos(dec) * jnp.sin(ra - ra0)
    mm = jnp.sin(dec) * jnp.cos(dec0) - jnp.cos(dec) * jnp.sin(dec0) * jnp.cos(ra - ra0)
    nn = jnp.sqrt(jnp.maximum(1.0 - ll * ll - mm * mm, 0.0)) - 1.0
    return ll, mm, nn
