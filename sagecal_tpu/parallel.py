"""Intra-subband baseline-axis sharding (SURVEY.md P1 / long-context).

The reference splits the ``Nbase*tilesz`` row axis across pthreads for
every predict/residual/cost/grad/Jacobian evaluation
(``predict.c:417-495``, ``thread_data_base_t``). The TPU-native
equivalent for one subband that spans MORE THAN ONE chip is not manual
collectives but sharding annotations + GSPMD: the solve is jitted with
its row-indexed inputs sharded over a "base" mesh axis and the solution
replicated; XLA's partitioner then runs every per-row computation
shard-local and inserts all-reduces exactly where the math contracts
over rows (residual norms, the 8N x 8N normal-equation accumulations,
LBFGS cost/grad sums, robust nu/weight statistics) — the whole solver
stack is reused unchanged.

This module provides the staging helper + sharded entry point and is
validated by a sharding-invariance test (``tests/test_scale.py``):
8-way row-sharded == single-device to float tolerance, with the row
count padded to the mesh when needed (padded rows get zero weight, which
every reduction in the stack already honors).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sagecal_tpu.solvers import sage


def base_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-axis mesh over the row (baseline x time) dimension."""
    devs = jax.devices()
    if n_devices:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("base",))


def pad_rows(arrays, wt_base, nrows: int, ndev: int):
    """Pad the leading row axis of every array (and the weight array) to
    a multiple of the mesh size. Padded rows carry zero weight: they are
    already excluded from every reduction the solvers perform (the same
    contract as flagged rows, lm.py make_weights)."""
    bpad = -(-nrows // ndev) * ndev
    if bpad == nrows:
        return list(arrays), np.asarray(wt_base), bpad
    out = []
    for a in arrays:
        a = np.asarray(a)
        pad_shape = (bpad - nrows,) + a.shape[1:]
        out.append(np.concatenate([a, np.zeros(pad_shape, a.dtype)]))
    wt = np.asarray(wt_base)
    wt = np.concatenate([wt, np.zeros((bpad - nrows,) + wt.shape[1:],
                                      wt.dtype)])
    return out, wt, bpad


def sharded_sagefit(mesh: Mesh, dsky, fdelta: float, chunk_mask,
                    n_stations: int, config=None,
                    with_shapelets: bool | None = None,
                    os_nsub: int = 0, dobeam: int = 0):
    """Build a row-sharded full solve: coherency predict + SAGE-EM with
    the [B]-indexed inputs sharded over ``mesh``'s "base" axis.

    Returns ``solve(x8, u, v, w, sta1, sta2, cidx, wt, J0_r8, freq,
    os_ids, key, tslot, beam)`` where cidx is [M, B] (sharded on its row
    axis), J0_r8 is the [M, K, N, 8] real Jones (replicated), os_ids the
    [B] ordered-subset ids (row-sharded; pass with ``os_nsub`` > 0 to
    keep the P4 acceleration on the sharded path), ``key`` the per-tile
    PRNG key (replicated), ``tslot`` [B] row timeslot indices
    (row-sharded) and ``beam`` a replicated BeamArrays pytree (or None
    with ``dobeam=0`` — beam tables are small and per (station, time),
    so they replicate while the row-indexed beam gathers shard).
    ``with_shapelets=None`` auto-detects from the sky model like the
    unsharded predict. The caller stages inputs with :func:`shard_rows`;
    outputs (J, res_0, res_1, mean_nu) come back replicated.

    Dtype policy (MIGRATION.md "Dtype policy"): ``x8``/``wt`` may be
    staged in the reduced storage dtype — ``config.dtype_policy``
    rides into sagefit, which owns the storage/accumulate split, so
    the row-sharded program moves storage-dtype [B]-rows and GSPMD's
    all-reduces contract f32 accumulators exactly like the unsharded
    path. Geometry (u, v, w) must keep the pipeline real dtype. No
    f32 fallback remains on this path (the PR 6 exemption melted in
    ISSUE 14; tolerance-gated in tests/test_dtype_policy.py).
    """
    from sagecal_tpu.rime import predict as rp
    from sagecal_tpu.solvers import normal_eq as ne

    cfg = config or sage.SageConfig()
    cmask_j = jnp.asarray(chunk_mask)
    rows = NamedSharding(mesh, P("base"))
    rows2 = NamedSharding(mesh, P(None, "base"))
    repl = NamedSharding(mesh, P())

    def solve(x8, u, v, w, sta1, sta2, cidx, wt, J0_r8, freq, os_ids,
              key, tslot, beam):
        coh = rp.coherencies(dsky, u, v, w, freq[None], fdelta,
                             with_shapelets=with_shapelets, beam=beam,
                             dobeam=dobeam, tslot=tslot, sta1=sta1,
                             sta2=sta2)[:, :, 0]
        os_id = (os_ids, os_nsub) if os_nsub else None
        J, info = sage.sagefit(x8, coh, sta1, sta2, cidx, cmask_j,
                               ne.jones_r2c(J0_r8), n_stations, wt,
                               config=cfg, os_id=os_id, key=key)
        return (ne.jones_c2r(J), info["res_0"], info["res_1"],
                info["mean_nu"])

    return jax.jit(
        solve,
        in_shardings=(rows, rows, rows, rows, rows, rows, rows2, rows,
                      repl, repl, rows, repl, rows, repl),
        out_shardings=(repl, repl, repl, repl))


def shard_rows(mesh: Mesh, *arrays, row_axis: int = 0):
    """Stage host arrays with their row axis sharded over "base"."""
    out = []
    for a in arrays:
        spec = [None] * np.asarray(a).ndim
        spec[row_axis] = "base"
        out.append(jax.device_put(jnp.asarray(a),
                                  NamedSharding(mesh, P(*spec))))
    return out
